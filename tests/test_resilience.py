"""Self-healing training (resilience/) — rollback-and-recover, preemption
shutdown, checkpoint integrity fallback.

The load-bearing pins, per pillar:

  * divergence recovery — a chaos ``nan_client`` run under
    ``--recover_policy retry`` COMPLETES and is bit-identical to the
    uninterrupted (chaos-free) run: final params AND the deduped scalar
    sequence (the determinism contract README documents); ``demote``
    lands on the expected rung with ``xla/retraces == 0`` across the
    recovery (the AOT-prewarm claim); ``skip_clients`` blacklists the
    suspect and the ledger still satisfies the live-byte exactness
    invariant (checker-enforced);
  * preemption — the seeded ``preempt@R`` chaos event exits through
    ``PreemptShutdown`` with a forced checkpoint from which ``--resume``
    reproduces the uninterrupted run bit-exactly;
  * integrity — a corrupted latest checkpoint restores from the previous
    retained step with a warning naming the rejected step and reason.

All through the REAL shared runner (train/runner.py) at TinyMLP scale —
the femnist cv_train twin is slow-marked per the tier-1 budget. The
``--recover_policy none`` constructs-NOTHING gate is pinned here too
(golden parity / level-0 HLO byte-identity is the existing
test_compress_parity / test_telemetry coverage — this file pins the
construction gate those tests rely on)."""

import json
import os
import signal

import numpy as np
import pytest
from test_round import BASE, _setup

from commefficient_tpu.data import FedDataset, FedSampler
from commefficient_tpu.fedsim import ChaosEvent, parse_chaos
from commefficient_tpu.fedsim.env import FedEnvironment
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.resilience import (
    EXIT_PREEMPTED,
    PreemptGuard,
    PreemptShutdown,
    RollbackVault,
    available_recover_policies,
    build_resilience,
)
from commefficient_tpu.utils.checkpoint import FedCheckpointer
from commefficient_tpu.utils.config import RECOVER_POLICIES, Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(REPO, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# config validation + registry + grammar
# ---------------------------------------------------------------------------

def test_recover_policy_registry_matches_config_tuple():
    assert available_recover_policies() == tuple(sorted(RECOVER_POLICIES))


@pytest.mark.parametrize("kw,match", [
    (dict(recover_policy="bogus"), r"recover_policy"),
    (dict(snapshot_every=0), r"snapshot_every"),
    (dict(max_recoveries=0), r"max_recoveries"),
    # detection rides the flight recorder: level 0 never fires it
    (dict(recover_policy="retry", telemetry_level=0), r"telemetry_level"),
    # demote needs a >= 2-rung control ladder to descend
    (dict(recover_policy="demote", telemetry_level=1), r"ladder"),
    (dict(recover_policy="demote", telemetry_level=1,
          control_policy="fixed", control_schedule="0-=0", ladder="k=60",
          mode="true_topk", error_type="virtual", k=60,
          topk_method="threshold"), r">= 2"),
    # skip_clients masks through the fedsim participation mask
    (dict(recover_policy="skip_clients", telemetry_level=1),
     r"fedsim|masking"),
])
def test_config_rejects_bad_resilience_knobs(kw, match):
    with pytest.raises(ValueError, match=match):
        Config(**kw)


def test_chaos_grammar_preempt_and_counted_nan():
    plan = parse_chaos("preempt@7")
    assert plan == (ChaosEvent("preempt", 7.0, 7, 7, 1),)
    # counted form: N clients over a rounds window
    plan = parse_chaos("nan_client@2:rounds=3-4")
    assert plan == (ChaosEvent("nan_client", 2.0, 3, 4, 2),)
    # the single-round equivalence the docstring promises
    assert parse_chaos("nan_client@1:rounds=5-5")[0].active(5)
    assert not parse_chaos("nan_client@1:rounds=5-5")[0].active(6)


@pytest.mark.parametrize("bad", [
    "preempt@-1",            # negative round
    "preempt@0.5",           # fractional round
    "preempt@3:rounds=1-2",  # preempt@R names its round directly
    "nan_client@0:rounds=1-2",  # counted form needs count >= 1
])
def test_chaos_grammar_rejects(bad):
    with pytest.raises(ValueError, match="chaos"):
        parse_chaos(bad)


def test_transient_nan_suppressed_on_replay():
    """fedsim transient-fault semantics: the nan_client injection fires on
    a round's FIRST execution only; every other draw (and so every mask)
    is bit-identical on replay — what makes a 'retry' recovery a
    bit-identical replay."""
    env = FedEnvironment(Config(
        num_workers=8, num_clients=16, seed=7, availability="bernoulli",
        dropout_prob=0.4, chaos="nan_client@2:rounds=3-3",
    ))
    first = env.round_env(3)
    replay = env.round_env(3, replay=True)
    assert first.corrupt.sum() == min(2, int(first.live.sum()))
    assert replay.corrupt.sum() == 0
    np.testing.assert_array_equal(first.live, replay.live)
    assert first.stats["fedsim/preempt"] == 0.0
    # preempt rides the stats, never the masks
    env_p = FedEnvironment(Config(num_workers=8, num_clients=16, seed=7,
                                  chaos="preempt@3"))
    assert env_p.round_env(3).stats["fedsim/preempt"] == 1.0
    assert env_p.round_env(2).stats["fedsim/preempt"] == 0.0


# ---------------------------------------------------------------------------
# construction gate + unit pieces
# ---------------------------------------------------------------------------

def test_default_config_constructs_nothing():
    """recover_policy='none' + no preemption source: build_resilience
    returns None, the session rider slot stays None, and the process
    signal table is untouched — the level-0/availability='always' gate
    discipline golden parity depends on."""
    cfg = Config(mode="uncompressed", **BASE)
    assert not cfg.recovery_enabled
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    before = (signal.getsignal(signal.SIGTERM),
              signal.getsignal(signal.SIGINT))
    assert build_resilience(cfg, sess, sampler) is None
    assert sess.resilience is None
    assert (signal.getsignal(signal.SIGTERM),
            signal.getsignal(signal.SIGINT)) == before


def test_preempt_guard_signals_install_and_restore():
    prev = (signal.getsignal(signal.SIGTERM),
            signal.getsignal(signal.SIGINT))
    guard = PreemptGuard(install_signals=True)
    assert guard.signals_installed
    assert signal.getsignal(signal.SIGTERM) == guard._on_signal
    guard.close()
    assert (signal.getsignal(signal.SIGTERM),
            signal.getsignal(signal.SIGINT)) == prev
    # flag semantics: chaos stat folds in; first source wins; idempotent
    g = PreemptGuard()
    assert not g.check_metrics({"fedsim/preempt": 0.0})
    assert g.check_metrics({"fedsim/preempt": 1.0})
    assert g.source == "chaos preempt@round"
    g.request("signal SIGTERM")
    assert g.source == "chaos preempt@round"  # first wins
    assert EXIT_PREEMPTED == 75  # sysexits EX_TEMPFAIL, README exit table


def test_vault_snapshot_restore_roundtrip_bitwise():
    """The vault restores the exact captured state (params, momentum,
    error, step, round clock) and a re-run of the same rounds reproduces
    the first pass — the retry policy's whole mechanism."""
    cfg = Config(mode="true_topk", error_type="virtual",
                 virtual_momentum=0.9, k=40, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    for r in range(3):
        ids, batch = sampler.sample_round(r)
        sess.train_round(ids, batch, 0.3)
    vault = RollbackVault(snapshot_every=3)
    assert vault.will_snapshot(3) and not vault.will_snapshot(2)
    vault.snapshot(sess, 3)
    at3 = np.asarray(sess.state.params_vec).copy()
    err3 = np.asarray(sess.state.error).copy()

    def two_more():
        for r in range(3, 5):
            ids, batch = sampler.sample_round(r)
            sess.train_round(ids, batch, 0.3)
        return np.asarray(sess.state.params_vec).copy()

    first_pass = two_more()
    assert not np.array_equal(at3, first_pass)
    snap = vault.latest(max_step=4)
    assert snap is not None and snap.step == 3
    assert vault.restore(sess, snap) == 3
    np.testing.assert_array_equal(np.asarray(sess.state.params_vec), at3)
    np.testing.assert_array_equal(np.asarray(sess.state.error), err3)
    assert int(np.asarray(sess.state.step)) == 3
    assert sess._round_clock == 3  # fedsim/chaos schedule re-synced
    np.testing.assert_array_equal(two_more(), first_pass)


def test_ledger_snapshot_state_roundtrip():
    from commefficient_tpu.telemetry import CommLedger

    bpr = {"upload_floats": 20, "download_floats": 100,
           "upload_bytes": 80, "download_bytes": 400}
    led = CommLedger(bpr, mode="true_topk", num_workers=8)
    for s in range(3):
        led.on_round(s)
    state = led.snapshot_state()
    for s in range(3, 6):
        led.on_round(s)
    assert led.rounds == 6
    led.load_snapshot_state(state)
    assert led.rounds == 3 and led.cum_up_bytes == 3 * 80
    # replaying bills exactly once: the exactness invariant survives
    for s in range(3, 6):
        led.on_round(s)
    assert led.cum_up_bytes == 6 * 80


def test_flight_rewind_drops_rolled_back_records():
    from commefficient_tpu.telemetry import FlightRecorder

    fl = FlightRecorder(logdir="", window=8)
    for s in range(6):
        fl.record(s, 0.1, {"loss": 1.0})
    fl.rewind(3)
    assert [r["step"] for r in fl.records] == [0, 1, 2]
    assert fl.last_step == 2
    fl.rewind(0)
    assert not fl.records and fl.last_step is None


# ---------------------------------------------------------------------------
# the shared runner at TinyMLP scale (default-tier acceptance twins)
# ---------------------------------------------------------------------------

_RUNNER_BASE = dict(
    mode="true_topk", error_type="virtual", virtual_momentum=0.9, k=40,
    topk_method="threshold", telemetry_level=1, perf_audit=False,
    availability="bernoulli", dropout_prob=0.25,
    num_epochs=1, pivot_epoch=1, lr_scale=0.1,
)


class _Rows:
    """Row-capturing stand-in for TableLogger (the epoch-table parity
    checks read the rows instead of the console)."""

    def __init__(self):
        self.rows = []

    def append(self, row):
        self.rows.append(dict(row))


def _run_loop(tmp_path, tag, ckpt_kw=None, table=None, **kw):
    """One TinyMLP run through the REAL shared runner (cv_train's
    train_loop adapter). 9 rounds (600 samples / (8 workers x 8 batch))."""
    from commefficient_tpu.train.cv_train import train_loop
    from commefficient_tpu.utils.logging import MetricsWriter

    base = {**BASE, "local_batch_size": 8}
    cfg = Config(**{**base, **_RUNNER_BASE, **(ckpt_kw or {}), **kw})
    ds, params, loss_fn = _setup(cfg.num_clients)
    test_ds = FedDataset({"x": ds.data["x"][:40], "y": ds.data["y"][:40]},
                         1, seed=0)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    run_dir = str(tmp_path / f"run{tag}")
    writer = MetricsWriter(run_dir, cfg=cfg)
    ck = FedCheckpointer(cfg)
    try:
        val = train_loop(cfg, sess, sampler, test_ds, writer, table=table,
                         eval_batch_size=32, checkpointer=ck)
    finally:
        ck.close()
        writer.close()
    return sess, run_dir, val


def _scalars(run_dir,
             exclude=("resilience/", "trace/",
                      "xla/exposed_collective_ms")):
    """metrics.jsonl as (name, value, step) in file order, deduped to the
    LAST occurrence per (name, step): a recovery replays its rolled-back
    rounds, so those steps legitimately appear twice — the healed values
    are the survivors the determinism contract compares.
    ``xla/exposed_collective_ms`` (v9) and ``trace/*`` (v11) are
    host-measured wall-clock, so excluded from bit-equality twins."""
    rows = {}
    with open(os.path.join(run_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "name" not in rec or rec["name"].startswith(exclude):
                continue
            rows[(rec["name"], rec["step"])] = (
                rec["name"], rec["value"], rec["step"])
    return list(rows.values())


def _last_value(run_dir, name):
    out = None
    with open(os.path.join(run_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if rec.get("name") == name:
                out = rec["value"]
    return out


@pytest.fixture(scope="module")
def uninterrupted(tmp_path_factory):
    """The chaos-free baseline run every recovery twin compares against —
    checkpointed every 2 rounds so it also pins the end-of-training
    force-save and seeds the integrity-fallback vault."""
    tmp = tmp_path_factory.mktemp("resil_base")
    ckpt_dir = str(tmp / "ckpt")
    rows = _Rows()
    sess, run_dir, val = _run_loop(
        tmp, "_base", table=rows,
        ckpt_kw=dict(checkpoint_dir=ckpt_dir, checkpoint_every=2),
    )
    return {
        "params": np.asarray(sess.state.params_vec).copy(),
        "step": int(np.asarray(sess.state.step)),
        "scalars": _scalars(run_dir),
        "table": rows.rows,
        "run_dir": run_dir,
        "ckpt_dir": ckpt_dir,
        "tmp": tmp,
        "val": val,
    }


def test_retry_heals_nan_client_bit_exactly(tmp_path, uninterrupted):
    """Acceptance pillar 1 (TinyMLP twin of the femnist e2e): a
    nan_client@1:rounds=5-5 injection under retry completes all 9 rounds,
    reports exactly one recovery, and the healed run is BIT-IDENTICAL to
    the uninterrupted run — final params and the deduped scalar sequence
    (ledger bytes included: the rollback rewound the accounting)."""
    rows = _Rows()
    sess, run_dir, _val = _run_loop(
        tmp_path, "_retry", table=rows,
        chaos="nan_client@1:rounds=5-5", recover_policy="retry",
        snapshot_every=4,
    )
    np.testing.assert_array_equal(np.asarray(sess.state.params_vec),
                                  uninterrupted["params"])
    assert _last_value(run_dir, "resilience/recoveries") == 1.0
    assert _last_value(run_dir, "resilience/rollback_round") == 4.0
    assert _scalars(run_dir) == uninterrupted["scalars"], (
        "a healed retry run must reproduce the uninterrupted scalars"
    )
    # the epoch TABLE row too: the accumulator rides the vault snapshot,
    # so the mid-epoch rollback (round 4 of 9) re-seeds rounds 0-3 and
    # the healed epoch averages the FULL epoch, bit-equal to baseline
    # (wall-clock columns excluded)
    times = {"train_time", "val_time"}
    assert [{k: v for k, v in r.items() if k not in times}
            for r in rows.rows] == [
        {k: v for k, v in r.items() if k not in times}
        for r in uninterrupted["table"]]
    # every artifact (incl. the _recovery-tagged flight dump and the
    # replay-rewound ledger) validates under schema v6
    mod = _checker()
    mod.validate_run_dir(run_dir)
    rec = json.loads(open(
        os.path.join(run_dir, "flight_5_recovery.json")).read())
    hist = rec["recovery_history"]
    assert len(hist) == 1 and hist[0]["outcome"] == "recovered"
    assert hist[0]["first_bad_step"] == 5 and hist[0]["rollback_to"] == 4
    # the detection-time dump preserved the diverged trajectory
    assert os.path.exists(os.path.join(run_dir, "flight_5.json"))


def test_retry_heals_under_pipelined_engine(tmp_path, uninterrupted):
    """The pipelined twin of the retry acceptance: at --pipeline_depth 2
    the recovery quiesces the in-flight prefetch window like a checkpoint
    fence (engine.restart), restages from the rollback round with
    replay=True semantics, and the healed run is STILL bit-identical to
    the uninterrupted (depth-0, chaos-free) run."""
    sess, run_dir, _val = _run_loop(
        tmp_path, "_retry_p2",
        chaos="nan_client@1:rounds=5-5", recover_policy="retry",
        snapshot_every=4, pipeline_depth=2,
    )
    np.testing.assert_array_equal(np.asarray(sess.state.params_vec),
                                  uninterrupted["params"])
    assert _last_value(run_dir, "resilience/recoveries") == 1.0
    # pipeline/* gauges exist only at depth > 0 — exclude them from the
    # cross-depth scalar comparison, like tests/test_pipeline.py does
    seq = _scalars(run_dir, exclude=("resilience/", "pipeline/", "trace/",
                                     "xla/exposed_collective_ms"))
    assert seq == uninterrupted["scalars"]


def test_retry_rollback_into_completed_epoch_no_duplicate_rows(tmp_path):
    """Review fix: a rollback landing INSIDE an already-completed epoch
    (divergence in epoch 1, newest snapshot mid-epoch 0) must not re-run
    that epoch's end block — the healed table would otherwise carry a
    duplicate epoch-0 row (and re-eval / re-write its val scalars)."""
    base_rows, heal_rows = _Rows(), _Rows()
    _run_loop(tmp_path, "_xepoch_base", table=base_rows, num_epochs=2)
    sess, run_dir, _val = _run_loop(
        tmp_path, "_xepoch_heal", table=heal_rows, num_epochs=2,
        # round 9 opens epoch 1; detection at the round-12 boundary drain
        # rolls back to the mid-epoch-0 snapshot at round 8
        chaos="nan_client@1:rounds=9-9", recover_policy="retry",
        snapshot_every=4,
    )
    assert _last_value(run_dir, "resilience/recoveries") == 1.0
    assert _last_value(run_dir, "resilience/rollback_round") == 8.0
    times = {"train_time", "val_time"}
    strip = lambda rows: [{k: v for k, v in r.items() if k not in times}
                          for r in rows]
    assert len(heal_rows.rows) == 2  # one row per epoch, no duplicate
    assert strip(heal_rows.rows) == strip(base_rows.rows)


def test_retry_exhaustion_reraises_with_history(tmp_path):
    """A PERSISTENT divergence (injection active on every execution, so
    the replay diverges again... modeled by an open-ended window wider
    than max_recoveries can outrun) gives up after --max_recoveries and
    re-raises the ORIGINAL DivergenceError with the full history."""
    from commefficient_tpu.telemetry import DivergenceError

    with pytest.raises(DivergenceError) as ei:
        _run_loop(
            tmp_path, "_exhaust",
            # replay suppresses already-executed rounds' injections, but
            # every recovery advances into rounds that inject on THEIR
            # first execution: each re-entry meets a fresh divergence
            # until the bound trips
            chaos="nan_client@1:rounds=3-8", recover_policy="retry",
            snapshot_every=2, max_recoveries=2,
        )
    hist = ei.value.recovery_history
    assert len(hist) == 3  # two recoveries + the give-up entry
    assert [h["outcome"] for h in hist[:2]] == ["recovered", "recovered"]
    assert "exhausted" in hist[-1]["outcome"]


def test_demote_recovery_descends_ladder_zero_retraces(tmp_path):
    """Acceptance pillar 1, demote flavor: the recovery floors the
    control/ ladder one rung cheaper through the AOT-prewarmed switch —
    the healed run finishes on rung 1, never climbs back above the floor,
    and xla/retraces stays 0 across the whole recovery."""
    sess, run_dir, _val = _run_loop(
        tmp_path, "_demote",
        mode="local_topk", error_type="local", local_momentum=0.9,
        virtual_momentum=0.0, k=60,
        control_policy="fixed", control_schedule="0-=0", ladder="k=60,30",
        chaos="nan_client@3", recover_policy="demote", snapshot_every=2,
    )
    seq = _scalars(run_dir, exclude=())
    rungs = [(s, v) for n, v, s in seq if n == "control/rung"]
    # rounds before the rollback ran rung 0; the healed replay (from
    # round 2 on) runs the demotion floor
    assert [v for s, v in rungs if s < 2] == [0.0, 0.0]
    assert all(v == 1.0 for s, v in rungs if s >= 2), rungs
    assert {v for n, v, _s in seq if n == "xla/retraces"} == {0.0}
    assert sess.retrace_sentinel.retraces == 0
    assert _last_value(run_dir, "resilience/rung_demotions") == 1.0
    assert _last_value(run_dir, "resilience/recoveries") == 1.0
    assert int(np.asarray(sess.state.step)) == 9  # completed all rounds


def test_preloop_failure_restores_signal_dispositions(tmp_path, monkeypatch):
    """Review fix: a failure BEFORE the runner's try/finally (e.g. the
    restore walk-back exhausted every retained step) must still restore
    the signal dispositions build_resilience installed — the surviving
    process would otherwise keep flag-only SIGTERM/SIGINT handlers
    nobody polls."""
    before = (signal.getsignal(signal.SIGTERM),
              signal.getsignal(signal.SIGINT))

    def boom(self, session, step=None):
        raise ValueError("restore failed at every retained checkpoint step")

    monkeypatch.setattr(FedCheckpointer, "restore", boom)
    with pytest.raises(ValueError, match="every retained"):
        _run_loop(
            tmp_path, "_preloop", preempt_signals=True,
            recover_policy="retry",
            ckpt_kw=dict(checkpoint_dir=str(tmp_path / "ck"),
                         checkpoint_every=2, resume=True),
        )
    assert (signal.getsignal(signal.SIGTERM),
            signal.getsignal(signal.SIGINT)) == before


def test_repeated_demote_descends_past_stale_snapshot_floor(tmp_path):
    """Review fix: the demotion floor is MONOTONE across rollback blob
    loads. With snapshot_every wider than an epoch the baseline snapshot
    (rung 0, floor 0) stays the only rollback target — a second
    divergence must still descend to rung 2, not re-demote to the rung 1
    that just diverged (the stale blob used to erase the floor)."""
    sess, run_dir, _val = _run_loop(
        tmp_path, "_demote2",
        mode="local_topk", error_type="local", local_momentum=0.9,
        virtual_momentum=0.0, k=60,
        control_policy="fixed", control_schedule="0-=0",
        ladder="k=60,30,15", num_epochs=2,
        # round 2 diverges in epoch 0 (detected at the epoch-end drain),
        # round 11 is past the first recovery's replay horizon so it
        # injects fresh in epoch 1 — both detections roll back to the
        # baseline snapshot at round 0 (snapshot_every=32 never fires
        # inside the 18-round run)
        chaos="nan_client@1:rounds=2-2,nan_client@1:rounds=11-11",
        recover_policy="demote", snapshot_every=32, max_recoveries=2,
    )
    assert int(np.asarray(sess.state.step)) == 18  # completed all rounds
    assert _last_value(run_dir, "resilience/recoveries") == 2.0
    assert _last_value(run_dir, "resilience/rung_demotions") == 2.0
    # the second recovery descends PAST the first demotion's rung
    assert _last_value(run_dir, "control/rung") == 2.0
    assert sess.controller.min_rung == 2
    assert sess.retrace_sentinel.retraces == 0


def test_skip_clients_recovery_blacklists_and_ledger_exact(tmp_path):
    """Acceptance pillar 1, skip_clients flavor: the suspect client is
    blacklisted out of every future participation mask, the run
    completes, and the ledger still satisfies the live-byte exactness
    invariant (checker-enforced + recomputed from the logged rates)."""
    sess, run_dir, _val = _run_loop(
        tmp_path, "_skip",
        mode="uncompressed", error_type="none", virtual_momentum=0.9,
        chaos="nan_client@3", recover_policy="skip_clients",
        snapshot_every=2,
    )
    assert int(np.asarray(sess.state.step)) == 9
    assert sess._client_blacklist is not None
    assert len(sess._client_blacklist) >= 1
    assert _last_value(run_dir, "resilience/blacklisted_clients") == float(
        len(sess._client_blacklist))
    mod = _checker()
    mod.validate_run_dir(run_dir)  # masked ledger invariant inside
    rates = [
        json.loads(line) for line in open(
            os.path.join(run_dir, "metrics.jsonl"))
        if '"fedsim/participation_rate"' in line
    ]
    # replayed steps appear twice; the rollback rewound the ledger, so
    # only the LAST (healed) billing per step survives in the totals
    live_sum = round(sum({r["step"]: r["value"]
                          for r in rates}.values()) * 8)
    ledger = json.loads(open(
        os.path.join(run_dir, "comm_ledger.json")).read())
    assert ledger["live_client_rounds"] == live_sum
    assert ledger["cum_up_bytes"] == (
        ledger["live_client_rounds"]
        * ledger["bytes_per_round"]["upload_bytes"]
    )


def test_skip_clients_blacklist_survives_checkpoint_resume(tmp_path):
    """Review fix: the session blacklist rides the checkpoint (a
    ``blacklist`` leaf in ``_to_saveable``) and restore re-condemns the
    saved clients — a preempt/resume cycle must not silently re-admit a
    client a recovery already blacklisted."""
    cfg = Config(**{**BASE, "local_batch_size": 8, **_RUNNER_BASE,
                    "mode": "uncompressed", "error_type": "none",
                    "chaos": "nan_client@3",
                    "recover_policy": "skip_clients",
                    "checkpoint_dir": str(tmp_path / "ck"),
                    "checkpoint_every": 2})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sess.blacklist_clients([3, 7])
    ck = FedCheckpointer(cfg)
    assert ck.maybe_save(sess, 2, force=True)
    ck.close()
    sess2 = FederatedSession(cfg, params, loss_fn)
    assert sess2._client_blacklist is None
    ck2 = FedCheckpointer(cfg)
    assert ck2.restore(sess2) == 0  # FedState.step at save time
    ck2.close()
    np.testing.assert_array_equal(sess2._client_blacklist, [3, 7])
    # and a blacklist-free checkpoint restored into a session that
    # already has one keeps the session's (template key absorbed)
    sess3 = FederatedSession(cfg, params, loss_fn)
    ck3 = FedCheckpointer(cfg.replace(
        checkpoint_dir=str(tmp_path / "ck2")))
    assert ck3.maybe_save(sess3, 2, force=True)  # no blacklist saved
    sess4 = FederatedSession(cfg, params, loss_fn)
    sess4.blacklist_clients([5])
    assert ck3.restore(sess4) == 0
    ck3.close()
    np.testing.assert_array_equal(sess4._client_blacklist, [5])


def test_recovery_discards_stale_checkpoints_above_rollback(tmp_path):
    """Review fix: a checkpoint saved between the rollback target and the
    detection point came from the rolled-back trajectory — under a
    demote fork it held the PRE-recovery controller blob (no min_rung
    floor), and the replay's maybe_save at that boundary used to be a
    silent no-op against it. The recovery now discards steps above the
    rollback so the replay re-saves its own state."""
    import orbax.checkpoint as ocp

    # snapshots at 4/8, checkpoint at 5; nan at 6 detected at the
    # snapshot-8 drain -> rollback to 4 < saved step 5
    _sess, run_dir, _val = _run_loop(
        tmp_path, "_stale",
        mode="local_topk", error_type="local", local_momentum=0.9,
        virtual_momentum=0.0, k=60,
        control_policy="fixed", control_schedule="0-=0", ladder="k=60,30",
        chaos="nan_client@6", recover_policy="demote", snapshot_every=4,
        ckpt_kw=dict(checkpoint_dir=str(tmp_path / "ck"),
                     checkpoint_every=5),
    )
    assert _last_value(run_dir, "resilience/recoveries") == 1.0
    assert _last_value(run_dir, "resilience/rollback_round") == 4.0
    mngr = ocp.CheckpointManager(os.path.abspath(str(tmp_path / "ck")))
    blob = np.asarray(mngr.restore(
        5, args=ocp.args.StandardRestore())["control"])
    mngr.close()
    # the step-5 checkpoint on disk is the REPLAY's: demoted rung (slot
    # 1) and the demotion floor (slot 7) both present — the stale
    # first-pass blob had 0 in both
    assert blob[1] == 1.0 and blob[7] == 1.0


def test_unavailable_policy_aborts_before_rewind(tmp_path):
    """Review fix: when the policy cannot act (here a second demotion
    with the 2-rung ladder already floored), the recovery aborts BEFORE
    the vault/ledger/flight rewind — the dead run's comm_ledger must
    describe the rounds that actually ran, not a rolled-back prefix."""
    from commefficient_tpu.telemetry import DivergenceError

    with pytest.raises(DivergenceError) as ei:
        _run_loop(
            tmp_path, "_unavail",
            mode="local_topk", error_type="local", local_momentum=0.9,
            virtual_momentum=0.0, k=60,
            control_policy="fixed", control_schedule="0-=0",
            ladder="k=60,30",
            chaos="nan_client@3,nan_client@6", recover_policy="demote",
            snapshot_every=2,
        )
    hist = ei.value.recovery_history
    assert [h["outcome"][:10] for h in hist] == ["recovered", "policy una"]
    assert "cheapest rung" in hist[-1]["outcome"]
    # drained rounds billed net of the FIRST (successful) rewind:
    # 0,1 + replayed 2,3 + 4,5 + the bad 6 (the drain bills it before
    # raising; 7 was pending and dropped) = 7 — an aborted second
    # recovery must NOT have rewound these to the snapshot-6 counters
    ledger = json.loads(open(os.path.join(
        str(tmp_path / "run_unavail"), "comm_ledger.json")).read())
    assert ledger["rounds"] == 7


def test_preempt_shutdown_message_honest_without_checkpointing():
    """Review fix: a preemption with checkpointing disabled must not
    claim a checkpoint was saved (the orchestrator would --resume into
    nothing and silently restart from round 0)."""
    e = PreemptShutdown(4, "signal SIGTERM", saved=False)
    assert not e.saved
    assert "NO checkpoint was saved" in str(e)
    assert "--resume to continue bit-exactly" not in str(e)
    assert str(EXIT_PREEMPTED) in str(e)
    assert PreemptShutdown(4, "x").saved  # checkpointed path unchanged


def test_preempt_chaos_forced_checkpoint_and_resume(tmp_path,
                                                    uninterrupted):
    """Acceptance pillar 2: the seeded preempt@3 event exits through
    PreemptShutdown AFTER draining + force-saving a checkpoint at the
    preempted round; a --resume run completes and reproduces the
    uninterrupted run bit-exactly."""
    ckpt_dir = str(tmp_path / "ckpt_pre")
    with pytest.raises(PreemptShutdown) as ei:
        _run_loop(
            tmp_path, "_pre",
            ckpt_kw=dict(checkpoint_dir=ckpt_dir, checkpoint_every=100),
            chaos="preempt@3",
        )
    assert ei.value.step == 4  # rounds 0..3 ran; saved at boundary 4
    assert ei.value.source == "chaos preempt@round"
    assert ei.value.saved  # the message's --resume promise is real
    ck = FedCheckpointer(Config(checkpoint_dir=ckpt_dir))
    assert ck.latest_step() == 4
    ck.close()
    run_pre = str(tmp_path / "run_pre")
    assert _last_value(run_pre, "resilience/preempt_requested") == 1.0
    # the crash teardown wrote the flight record naming the preemption
    flights = [f for f in os.listdir(run_pre) if f.startswith("flight_")]
    assert flights
    rec = json.loads(open(os.path.join(run_pre, flights[0])).read())
    assert "preemption requested" in rec["reason"]
    # resume: round 3 is behind the restore point, so the chaos event
    # never re-fires; the tail reproduces the uninterrupted run
    sess, _run_dir, _val = _run_loop(
        tmp_path, "_pre_resume",
        ckpt_kw=dict(checkpoint_dir=ckpt_dir, checkpoint_every=100),
        chaos="preempt@3", resume=True,
    )
    np.testing.assert_array_equal(np.asarray(sess.state.params_vec),
                                  uninterrupted["params"])


def test_end_of_training_checkpoint_and_resume_after_completion(
        uninterrupted):
    """Satellite: a completed run force-saves its FINAL state (odd-round
    tails included), so --resume on a finished run re-trains NOTHING —
    it restores, skips the epoch loop, and still returns final metrics."""
    ck = FedCheckpointer(Config(
        checkpoint_dir=uninterrupted["ckpt_dir"]))
    assert ck.latest_step() == 9 == uninterrupted["step"]
    ck.close()
    sess, run_dir, val = _run_loop(
        uninterrupted["tmp"], "_postresume",
        ckpt_kw=dict(checkpoint_dir=uninterrupted["ckpt_dir"],
                     checkpoint_every=2),
        resume=True,
    )
    assert int(np.asarray(sess.state.step)) == 9
    np.testing.assert_array_equal(np.asarray(sess.state.params_vec),
                                  uninterrupted["params"])
    assert val and np.isfinite(val["loss"])
    # no round trained, no train scalar written
    assert not [r for r in _scalars(run_dir) if r[0] == "train/loss"]
    # and the finished run's checkpoint was NOT redundantly re-saved
    ck = FedCheckpointer(Config(
        checkpoint_dir=uninterrupted["ckpt_dir"]))
    assert ck.latest_step() == 9
    ck.close()


def test_corrupted_latest_checkpoint_falls_back_with_warning(
        uninterrupted, tmp_path):
    """Acceptance pillar 3: a corrupted latest step is REJECTED by the
    manifest verification with a warning naming the step and reason, and
    restore falls back to the previous retained step; an explicitly
    requested step stays strict (raises, never substitutes)."""
    import shutil

    ckpt_dir = str(tmp_path / "ckpt_corrupt")
    shutil.copytree(uninterrupted["ckpt_dir"], ckpt_dir)
    cfg = Config(**{**BASE, "local_batch_size": 8}, **_RUNNER_BASE,
                 checkpoint_dir=ckpt_dir, checkpoint_every=2)
    ck = FedCheckpointer(cfg)
    steps = sorted(int(s) for s in ck.mngr.all_steps())
    latest, prev = steps[-1], steps[-2]
    # flip bytes in one payload file of the latest step (size preserved:
    # only the sha256 catches it)
    victim = None
    for dirpath, _dirs, files in os.walk(os.path.join(ckpt_dir,
                                                      str(latest))):
        for fn in files:
            p = os.path.join(dirpath, fn)
            if os.path.getsize(p) > 16:
                victim = p
                break
        if victim:
            break
    with open(victim, "r+b") as f:
        data = bytearray(f.read())
        data[-8:] = bytes(8) if bytes(data[-8:]) != bytes(8) else b"\xff" * 8
        f.seek(0)
        f.write(data)
    reason = ck.verify_step(latest)
    assert reason is not None and "sha256 mismatch" in reason
    assert ck.verify_step(prev) is None
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    with pytest.warns(UserWarning, match=rf"step {latest} REJECTED"):
        assert ck.restore(sess) == prev
    assert int(np.asarray(sess.state.step)) == prev
    # explicit step: the caller named it — strict rejection, no fallback
    sess2 = FederatedSession(cfg, params, loss_fn)
    with pytest.raises(ValueError, match="integrity"):
        ck.restore(sess2, step=latest)
    ck.close()
    # truncation is caught by the cheaper size check
    ck2 = FedCheckpointer(cfg)
    with open(victim, "r+b") as f:
        f.truncate(os.path.getsize(victim) - 4)
    assert "size mismatch" in ck2.verify_step(latest)
    ck2.close()


def test_restore_exhausting_all_steps_chains_failures(tmp_path,
                                                      uninterrupted):
    """Every retained step rejected -> the final error names each step
    with its reason instead of silently reporting only the last."""
    import shutil

    ckpt_dir = str(tmp_path / "ckpt_all_bad")
    shutil.copytree(uninterrupted["ckpt_dir"], ckpt_dir)
    cfg = Config(**{**BASE, "local_batch_size": 8}, **_RUNNER_BASE,
                 checkpoint_dir=ckpt_dir, checkpoint_every=2)
    ck = FedCheckpointer(cfg)
    steps = sorted(int(s) for s in ck.mngr.all_steps())
    for s in steps:  # tamper EVERY manifest's expectations
        mpath = os.path.join(ckpt_dir, "manifests", f"{s}.json")
        man = json.loads(open(mpath).read())
        for info in man["files"].values():
            info["sha256"] = "0" * 64
        with open(mpath, "w") as f:
            json.dump(man, f)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    with pytest.warns(UserWarning):
        with pytest.raises(ValueError) as ei:
            ck.restore(sess)
    for s in steps:
        assert f"step {s}" in str(ei.value)
    ck.close()


def test_restore_template_walk_chains_all_candidate_failures(tmp_path):
    """Satellite: when EVERY rung state template fails to restore (here a
    genuinely corrupted payload on a shape-changing ladder, the exact
    masking hazard: the bare-except walk used to surface only the LAST
    layout's error), the error names each attempt and chains the FIRST —
    the likely save-time layout — as the cause."""
    import glob
    import shutil

    from commefficient_tpu.control import build_controller

    def build():
        kw = dict(BASE)
        kw.update(mode="powersgd", error_type="virtual",
                  virtual_momentum=0.9, powersgd_rank=4,
                  telemetry_level=1, control_policy="fixed",
                  control_schedule="0-=0", ladder="powersgd_rank=4,2",
                  checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
        cfg = Config(**kw)
        _ds, params, loss_fn = _setup(cfg.num_clients)
        sess = FederatedSession(cfg, params, loss_fn)
        build_controller(cfg, sess, num_rounds=4)
        return cfg, sess

    cfg, sess = build()
    ck = FedCheckpointer(cfg)
    assert ck.maybe_save(sess, 2, force=True)
    ck.close()
    # strip the integrity sidecars (a legacy checkpoint: nothing to
    # pre-verify, so restore reaches the template walk) and corrupt the
    # payload so EVERY rung template's attempt fails
    shutil.rmtree(str(tmp_path / "ck" / "manifests"))
    victims = [p for p in glob.glob(str(tmp_path / "ck" / "2" / "**"),
                                    recursive=True) if os.path.isfile(p)]
    os.remove(victims[-1])
    _cfg2, sess2 = build()
    ck2 = FedCheckpointer(cfg)
    with pytest.raises(ValueError, match="every rung state template") as ei:
        ck2.restore(sess2, step=2)
    msg = str(ei.value)
    assert "rung 0 template" in msg and "rung 1 template" in msg
    assert ei.value.__cause__ is not None  # the FIRST attempt's failure
    ck2.close()


def test_checkpointer_closed_on_crash_path(tmp_path):
    """Satellite: the shared runner's finally block closes the Orbax
    manager on crash paths (it used to leak there), and close() is
    idempotent so the entries' own finally stays a no-op."""
    class _Poisoned:
        def __init__(self, real):
            self._real = real

        def steps_per_epoch(self):
            return self._real.steps_per_epoch()

        def epoch(self, e):
            for r, item in enumerate(self._real.epoch(e)):
                if r == 2:
                    raise ValueError("poisoned round 2")
                yield item

        def sample_round(self, r):
            return self._real.sample_round(r)

    from commefficient_tpu.train.cv_train import train_loop
    from commefficient_tpu.utils.logging import MetricsWriter

    cfg = Config(**{**BASE, "local_batch_size": 8}, **_RUNNER_BASE,
                 checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=1)
    ds, params, loss_fn = _setup(cfg.num_clients)
    test_ds = FedDataset({"x": ds.data["x"][:40], "y": ds.data["y"][:40]},
                         1, seed=0)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = _Poisoned(FedSampler(ds, num_workers=cfg.num_workers,
                                   local_batch_size=cfg.local_batch_size,
                                   seed=1))
    writer = MetricsWriter(str(tmp_path / "run"), cfg=cfg)
    ck = FedCheckpointer(cfg)
    with pytest.raises(ValueError, match="poisoned round 2"):
        train_loop(cfg, sess, sampler, test_ds, writer,
                   eval_batch_size=32, checkpointer=ck)
    writer.close()
    assert ck.mngr is None, "runner's finally must close the checkpointer"
    ck.close()  # the entry-level belt: idempotent, not a double-close


# ---------------------------------------------------------------------------
# cv_train e2e (slow femnist twin of the TinyMLP acceptance above)
# ---------------------------------------------------------------------------

@pytest.mark.slow  # two femnist/resnet9 cv_main runs (~2 min CPU compiles);
# every claim holds default-tier coverage through the TinyMLP runner twins
def test_cv_train_retry_heals_femnist_e2e(tmp_path):
    """The full-entry acceptance: cv_train with
    chaos "nan_client@1:rounds=5-5" + --recover_policy retry completes
    all rounds, reports resilience/recoveries == 1, and its final
    checkpointed params match the chaos-free run's bit-exactly."""
    import orbax.checkpoint as ocp

    from commefficient_tpu.train.cv_train import main as cv_main

    def kw(tag, **extra):
        return dict(
            dataset_name="femnist", model="resnet9", mode="local_topk",
            error_type="local", k=2000, num_clients=6, num_workers=4,
            num_devices=4, local_batch_size=32, num_epochs=2,
            pivot_epoch=1, lr_scale=0.1, telemetry_level=1,
            perf_audit=False, availability="bernoulli", dropout_prob=0.3,
            dataset_dir=str(tmp_path), seed=0,
            checkpoint_dir=str(tmp_path / f"ckpt{tag}"),
            checkpoint_every=100,  # only the end-of-training force-save
            logdir=str(tmp_path / f"runs{tag}"), **extra,
        )

    def final_params(tag):
        mngr = ocp.CheckpointManager(
            os.path.abspath(str(tmp_path / f"ckpt{tag}")))
        fs = mngr.restore(mngr.latest_step(),
                          args=ocp.args.StandardRestore())["fed_state"]
        mngr.close()
        return np.asarray(fs["params_vec"])

    val = cv_main([], **kw("_clean"))
    assert np.isfinite(val["loss"])
    val = cv_main([], **kw("_chaos", chaos="nan_client@1:rounds=5-5",
                           recover_policy="retry", snapshot_every=4))
    assert np.isfinite(val["loss"])
    run = sorted((tmp_path / "runs_chaos").iterdir())[0]
    assert _last_value(str(run), "resilience/recoveries") == 1.0
    np.testing.assert_array_equal(final_params("_chaos"),
                                  final_params("_clean"))
    _checker().validate_run_dir(run)
