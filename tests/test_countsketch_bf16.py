"""bf16 sketch tables with f32 accumulation (CountSketch.table_dtype).

The compress/ LINEAR contract is what makes the cross-worker psum exact:
``encode(a) + encode(b) == encode(a + b)``. With bf16-STORED tables that
contract holds to a pinned tolerance instead of bit-exactly (each
downcast costs ~2^-8 relative; accumulation itself stays f32) — pinned
here together with the properties the round engines lean on:

  * linearity within tolerance (the psum-safety contract) AND the fedsim
    masking commute (a masked client's zero transmit sketches to exactly
    zero in any dtype);
  * the f32 DEFAULT is bit-untouched — table dtype, values, and the
    golden-recording path (tests/test_compress_parity.py keeps pinning
    that end to end);
  * estimation upcasts (bf16 table round-trips recover planted heavy
    hitters);
  * byte accounting: a bf16-table compressor reports 2 B/float through
    ``upload_bytes_per_float`` and the session's bytes_per_round halves
    the uplink — the ledger/HLO cross-check arithmetic;
  * session-level training with bf16 tables stays close to the f32 twin
    (loose tolerance: error feedback compounds the rounding by design).
"""

import jax
import jax.numpy as jnp
import numpy as np
from test_round import BASE, _setup

from commefficient_tpu.compress import get_compressor
from commefficient_tpu.data import FedSampler
from commefficient_tpu.ops.countsketch import (
    CountSketch,
    estimate_all,
    sketch_vec,
)
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.utils.config import Config

D, C, R = 10_000, 2_000, 5


def _spec(**kw):
    return CountSketch(d=D, c=C, r=R, seed=7, **kw)


def test_f32_default_bit_untouched():
    """table_dtype defaults to f32 and the downcast is a no-op: the table
    is IDENTICAL to one from a spec that never heard of table_dtype
    (same field left at default) — the golden-parity guarantee at the
    ops level."""
    spec = _spec()
    assert spec.table_dtype == jnp.float32
    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.normal(size=D).astype(np.float32))
    t = sketch_vec(spec, v)
    assert t.dtype == jnp.float32
    t_explicit = sketch_vec(_spec(table_dtype=jnp.float32), v)
    np.testing.assert_array_equal(np.asarray(t), np.asarray(t_explicit))


def test_bf16_linearity_within_pinned_tolerance():
    """sketch(a) + sketch(b) vs sketch(a + b) under bf16 storage: equal
    to within the bf16 rounding of the three downcasts — the LINEAR
    psum-safety contract at its pinned tolerance (bit-exact would be
    wrong to claim; a blown tolerance means accumulation left f32)."""
    spec = _spec(table_dtype=jnp.bfloat16)
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.normal(size=D).astype(np.float32))
    b = jnp.asarray(rng.normal(size=D).astype(np.float32))
    ta, tb = sketch_vec(spec, a), sketch_vec(spec, b)
    tab = sketch_vec(spec, a + b)
    assert ta.dtype == tb.dtype == tab.dtype == jnp.bfloat16
    lhs = np.asarray(ta, np.float32) + np.asarray(tb, np.float32)
    rhs = np.asarray(tab, np.float32)
    scale = np.abs(rhs).max()
    # 3 downcasts at ~2^-8 relative each; 2e-2 * scale is ~5x headroom
    np.testing.assert_allclose(lhs, rhs, rtol=0, atol=2e-2 * scale)
    # and bf16 really differs from f32 (the tolerance is not vacuous)
    f32 = np.asarray(sketch_vec(_spec(), a + b))
    assert np.abs(rhs - f32).max() > 0


def test_bf16_masked_zero_transmit_is_exact_zero():
    """fedsim psum-safety: a masked-out client's zero transmit must
    sketch to EXACTLY zero in any storage dtype (jnp.where gates the
    transmit before the encode — zero in, zero table out), so masking
    still commutes with the encode."""
    spec = _spec(table_dtype=jnp.bfloat16)
    t = sketch_vec(spec, jnp.zeros(D, jnp.float32))
    assert np.all(np.asarray(t, np.float32) == 0.0)


def test_bf16_roundtrip_recovers_heavy_hitters():
    spec = _spec(table_dtype=jnp.bfloat16)
    rng = np.random.default_rng(2)
    v = rng.normal(0, 1.0, size=D).astype(np.float32)
    hh = rng.choice(D, size=10, replace=False)
    v[hh] += 100.0 * rng.choice([-1.0, 1.0], size=10)
    est = np.asarray(estimate_all(spec, sketch_vec(spec, jnp.asarray(v))))
    assert est.dtype == np.float32  # estimation upcasts
    top = np.argsort(-np.abs(est))[:32]
    assert set(hh.tolist()) <= set(top.tolist())
    # bf16 ulp at |v|~100 is 0.5 and collision noise at d/c=5 adds ~1-2:
    # recovery-to-a-few-percent is the property, not fp32 accuracy
    np.testing.assert_allclose(est[hh], v[hh], rtol=5e-2)


def _cfg(**kw):
    return Config(**{**BASE, "mode": "sketch", "error_type": "virtual",
                     "virtual_momentum": 0.9, "k": 40, "num_rows": 3,
                     "num_cols": 256, "topk_method": "threshold", **kw})


def test_bf16_bytes_accounting_halves_uplink():
    cfg32, cfg16 = _cfg(), _cfg(sketch_table_dtype="bfloat16")
    d = 4096
    comp32 = get_compressor(cfg32, d=d, spec=CountSketch(d=d, c=256, r=3))
    comp16 = get_compressor(
        cfg16, d=d,
        spec=CountSketch(d=d, c=256, r=3, table_dtype=jnp.bfloat16),
    )
    assert comp32.upload_bytes_per_float() == 4
    assert comp16.upload_bytes_per_float() == 2
    assert (comp16.masked_upload_floats(5)
            == comp32.masked_upload_floats(5))  # floats unchanged


def test_bf16_session_bytes_and_training_close_to_f32():
    ds, params, loss_fn = _setup(12)

    def run(cfg):
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=cfg.num_workers,
                             local_batch_size=cfg.local_batch_size, seed=1)
        for r in range(4):
            ids, batch = sampler.sample_round(r)
            m = sess.train_round(ids, batch, 0.2)
        return sess, float(np.asarray(m["loss"]))

    s32, l32 = run(_cfg())
    s16, l16 = run(_cfg(sketch_table_dtype="bfloat16"))
    # uplink bytes really halve; float counts identical
    b32, b16 = s32.bytes_per_round(), s16.bytes_per_round()
    assert b16["upload_floats"] == b32["upload_floats"]
    assert b16["upload_bytes"] * 2 == b32["upload_bytes"]
    # state tables carry the storage dtype
    assert s16.state.momentum.dtype == jnp.bfloat16
    assert s16.state.error.dtype == jnp.bfloat16
    assert s32.state.momentum.dtype == jnp.float32
    # training tracks the f32 twin (loose: EF compounds bf16 rounding)
    p32 = np.asarray(s32.state.params_vec)
    p16 = np.asarray(s16.state.params_vec)
    scale = np.abs(p32).max()
    assert np.abs(p32 - p16).max() < 0.1 * scale
    assert np.isfinite(l16) and abs(l16 - l32) < 0.5


def test_bf16_controller_masked_accounting_uses_2_bytes_per_float():
    """The BudgetController's masked byte arithmetic promises to mirror
    the CommLedger EXACTLY — under bf16 tables both must bill the psum
    payload at 2 B/float (a hardcoded 4 double-billed the budget and
    fired BudgetExhaustedError at half the real spend)."""
    from commefficient_tpu.control import build_controller

    ds, params, loss_fn = _setup(12)
    cfg = _cfg(sketch_table_dtype="bfloat16", telemetry_level=1,
               availability="bernoulli", dropout_prob=0.25,
               control_policy="budget_pacing", budget_mb=500.0)
    sess = FederatedSession(cfg, params, loss_fn)
    ctrl = build_controller(cfg, sess, num_rounds=10)
    live, avail = 6, 8
    comp = sess.compressor
    want_up = comp.upload_bytes_per_float() * comp.masked_upload_floats(live)
    assert comp.upload_bytes_per_float() == 2
    bpr = sess.bytes_per_round()
    assert ctrl.round_bytes(0, live, avail) == (
        want_up + avail * bpr["download_bytes"]
    )
    ctrl._spend(0, live, avail)
    assert ctrl.spent_up == want_up


def test_bf16_sharded_decode_matches_dense_decode_bf16():
    """The sharded decode under bf16 tables agrees with the DENSE decode
    under the same bf16 tables (both pay identical storage rounding at
    the state boundaries; the decode algebra itself runs f32 in both) —
    the PR-6 parity claim carried over to the new dtype."""
    ds, params, loss_fn = _setup(12)

    def run(decode):
        cfg = _cfg(sketch_table_dtype="bfloat16", sketch_decode=decode)
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=cfg.num_workers,
                             local_batch_size=cfg.local_batch_size, seed=1)
        for r in range(3):
            ids, batch = sampler.sample_round(r)
            sess.train_round(ids, batch, 0.2)
        return np.asarray(sess.state.params_vec)

    p_dense = run("dense")
    p_shard = run("sharded")
    scale = max(np.abs(p_dense).max(), 1.0)
    # the two decodes round differently only where bf16 boundaries meet
    # k-sparse extraction ties; the algebra itself is the pinned PR-6
    # equivalence — atol scaled like the f32 test's 1e-6 plus bf16 slack
    np.testing.assert_allclose(p_shard, p_dense, rtol=0, atol=5e-3 * scale)
