"""clientstore/ — host-resident per-client state (store, LRU cache,
cohort streamer, round integration).

Parity contract (what these tests pin, and why):

  * host vs mmap vs host+cache share ONE compiled round program (rows
    arrive as jit arguments either way), so they are compared BITWISE —
    params, banks, and the drained scalar sequence.
  * host vs device are DIFFERENT XLA programs (the device round fuses an
    in-graph [C, D] gather/scatter; the hosted round takes [W, D] rows as
    donated arguments), and XLA's FMA/fusion choices differ across
    programs: under ``jax.disable_jit()`` the two paths are bit-identical,
    under jit the participants' bank rows pick up scattered 1-ulp
    differences (observed max 3e-8). That is the same cross-program
    reality the seed's own placement-knob pin accepts
    (test_round.py::test_offloaded_client_state_matches_hbm_resident uses
    allclose(1e-6)), so hosted-vs-device pins the drained loss sequence
    exactly (held empirically) and params at the established
    allclose(atol=1e-6).
"""

import json
import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from commefficient_tpu.clientstore import (
    CohortStreamer,
    HostStore,
    LRURowCache,
    available_stores,
    build_store,
    register,
)
from commefficient_tpu.data import FedSampler
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.utils.config import CLIENT_STORES, Config

from tests.test_round import BASE, _final_vec, _setup

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# both client banks live: local error feedback + local momentum
KW = dict(mode="local_topk", error_type="local", local_momentum=0.9, k=30)


def _checker():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(REPO, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# store contract
# ---------------------------------------------------------------------------

def test_registry_mirrors_config_client_stores():
    assert available_stores() == tuple(sorted(CLIENT_STORES))


def test_register_duplicate_rejected():
    with pytest.raises(ValueError, match="duplicate client store"):
        register("host")(HostStore)


def test_build_store_unknown_kind():
    with pytest.raises(ValueError, match="unknown client store"):
        build_store("bogus", num_rows=4, row_dim=2)


@pytest.mark.parametrize("kind", ["host", "mmap", "device"])
def test_gather_scatter_roundtrip(kind, tmp_path):
    path = str(tmp_path / "bank.vel") if kind == "mmap" else ""
    store = build_store(kind, num_rows=6, row_dim=3, path=path)
    rows = np.arange(6, dtype=np.float32).reshape(2, 3)
    store.scatter_rows(np.array([1, 4]), rows)
    np.testing.assert_array_equal(store.gather_rows(np.array([4, 1])),
                                  rows[::-1])
    full = np.asarray(store.array())
    np.testing.assert_array_equal(full[[1, 4]], rows)
    assert not full[[0, 2, 3, 5]].any()  # untouched rows stay zero
    # whole-bank load (checkpoint restore path) round-trips
    bank = np.random.default_rng(0).normal(size=(6, 3)).astype(np.float32)
    store.load(bank)
    np.testing.assert_array_equal(np.asarray(store.array()), bank)
    store.close()


def test_mmap_persists_across_reopen(tmp_path):
    path = str(tmp_path / "bank.err")
    store = build_store("mmap", num_rows=5, row_dim=4, path=path)
    rows = np.full((2, 4), 7.0, np.float32)
    store.scatter_rows(np.array([0, 3]), rows)
    store.flush()
    store.close()
    assert os.path.exists(path)  # a named bank survives close
    again = build_store("mmap", num_rows=5, row_dim=4, path=path)
    np.testing.assert_array_equal(again.gather_rows(np.array([0, 3])), rows)
    again.close()


def test_mmap_anonymous_bank_is_cleaned_up():
    store = build_store("mmap", num_rows=3, row_dim=2)
    path = store.path
    assert os.path.exists(path)
    store.close()
    assert not os.path.exists(path)  # owned tempfile unlinked


# ---------------------------------------------------------------------------
# LRU device cache
# ---------------------------------------------------------------------------

def test_lru_eviction_write_through():
    written = {}
    cache = LRURowCache(2, written.__setitem__)
    cache.put(10, "a")
    cache.put(11, "b")
    assert cache.get(10) == "a" and cache.hits == 1
    assert cache.get(99) is None and cache.misses == 1
    cache.put(12, "c")  # capacity 2: evicts LRU entry (11)
    assert cache.evictions == 1 and written == {11: "b"}
    assert 11 not in cache and 10 in cache and 12 in cache
    cache.flush()  # remaining dirty rows write through, stay cached
    assert written == {11: "b", 10: "a", 12: "c"}
    written.clear()
    cache.flush()  # now clean: nothing to write
    assert written == {}
    cache.invalidate()  # drop WITHOUT writeback (restore path)
    assert len(cache) == 0 and written == {}


# ---------------------------------------------------------------------------
# streamer: staleness versioning + async writeback fence
# ---------------------------------------------------------------------------

def test_streamer_staleness_and_writeback_fence():
    s = CohortStreamer(
        vel_store=HostStore(num_rows=8, row_dim=2),
        err_store=HostStore(num_rows=8, row_dim=2),
        num_clients=8,
    )
    cohort = s.gather(np.array([1, 2]))
    assert not s.is_stale(np.array([1, 2]), cohort.version)
    new = np.ones((2, 2), np.float32)
    s.scatter(np.array([2, 5]), new, 2 * new)
    # overlap (client 2) -> stale; disjoint cohort -> still fresh
    assert s.is_stale(np.array([1, 2]), cohort.version)
    assert not s.is_stale(np.array([1, 3]), cohort.version)
    # a regather observes the async write (gather waits on the pending
    # entry for overlapping ids)
    fresh = s.gather(np.array([2, 5]))
    np.testing.assert_array_equal(fresh.vel, new)
    np.testing.assert_array_equal(fresh.err, 2 * new)
    s.flush()
    np.testing.assert_array_equal(s.vel_array()[[2, 5]], new)
    stats = s.pop_round_stats()
    assert set(stats) == {"clientstore/cache_hit_rate",
                          "clientstore/evictions",
                          "clientstore/h2d_stage_ms",
                          "clientstore/writeback_ms"}
    s.close()


def test_streamer_load_invalidates_staged_cohorts():
    s = CohortStreamer(vel_store=HostStore(num_rows=4, row_dim=2),
                       num_clients=4)
    cohort = s.gather(np.array([0, 1]))
    bank = np.full((4, 2), 3.0, np.float32)
    s.load_vel(bank)  # checkpoint/vault restore
    assert s.is_stale(np.array([0, 1]), cohort.version)
    np.testing.assert_array_equal(s.gather(np.array([2])).vel, bank[[2]])
    assert s.gather(np.array([0])).err == ()  # absent bank convention
    s.close()


# ---------------------------------------------------------------------------
# e2e parity (device | host | mmap | host+cache)
# ---------------------------------------------------------------------------

def _run_store(n_rounds=5, **overrides):
    cfg = Config(**{**KW, **BASE, "telemetry_level": 1, **overrides})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    losses, metrics = [], []
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, 0.3)
        losses.append(float(m["loss"]))
        metrics.append(m)
    out = dict(
        losses=np.asarray(losses),
        params=_final_vec(sess).copy(),
        vel=None if sess.host_vel is None else np.asarray(sess.host_vel).copy(),
        err=None if sess.host_err is None else np.asarray(sess.host_err).copy(),
        metrics=metrics,
        retraces=sess.retrace_sentinel.retraces,
        hosted=sess._streamer is not None,
        state_vel=sess.state.client_vel,
    )
    sess.close_client_store()
    return out


@pytest.fixture(scope="module")
def parity(tmp_path_factory):
    tmp = tmp_path_factory.mktemp("clientstore")
    return {
        "device": _run_store(),
        "host": _run_store(client_store="host"),
        "mmap": _run_store(client_store="mmap",
                           client_store_path=str(tmp / "bank")),
        "cached": _run_store(client_store="host",
                             client_store_cache_rows=4),
    }


def test_hosted_variants_bitwise_identical(parity):
    """host / mmap / host+cache run the SAME compiled program — bitwise."""
    ref = parity["host"]
    for name in ("mmap", "cached"):
        run = parity[name]
        np.testing.assert_array_equal(ref["params"], run["params"], err_msg=name)
        np.testing.assert_array_equal(ref["vel"], run["vel"], err_msg=name)
        np.testing.assert_array_equal(ref["err"], run["err"], err_msg=name)
        np.testing.assert_array_equal(ref["losses"], run["losses"], err_msg=name)


def test_hosted_matches_device_store(parity):
    """Cross-program pin (see module docstring): exact loss sequence,
    params at the seed's established placement tolerance."""
    dev, host = parity["device"], parity["host"]
    np.testing.assert_array_equal(dev["losses"], host["losses"])
    np.testing.assert_allclose(dev["params"], host["params"], atol=1e-6)
    # the hosted banks track the device-resident ones to the same ulp noise
    np.testing.assert_allclose(np.asarray(parity["device"]["state_vel"]),
                               host["vel"], atol=1e-6)


def test_hosted_state_has_no_client_banks(parity):
    assert parity["host"]["hosted"] and parity["host"]["state_vel"] == ()
    assert not parity["device"]["hosted"]
    assert np.abs(parity["host"]["vel"]).sum() > 0  # momentum actually flowed


def test_zero_retraces_all_stores(parity):
    for name, run in parity.items():
        assert run["retraces"] == 0, name


def test_clientstore_scalars_ride_metrics(parity):
    keys = {"clientstore/cache_hit_rate", "clientstore/evictions",
            "clientstore/h2d_stage_ms", "clientstore/writeback_ms"}
    for m in parity["cached"]["metrics"]:  # constant key set, every round
        assert keys <= set(m)
        assert 0.0 <= m["clientstore/cache_hit_rate"] <= 1.0
        ev = m["clientstore/evictions"]
        assert ev >= 0 and float(ev) == int(ev)
        assert m["clientstore/h2d_stage_ms"] >= 0
        assert m["clientstore/writeback_ms"] >= 0
    # cache of 4 rows under an 8-worker cohort must actually evict
    assert sum(m["clientstore/evictions"]
               for m in parity["cached"]["metrics"]) > 0
    # device store (or any un-hosted run) carries NO clientstore scalars
    for m in parity["device"]["metrics"]:
        assert not keys & set(m)


def test_clientstore_scalars_absent_at_level_zero():
    run = _run_store(n_rounds=1, client_store="host", telemetry_level=0)
    assert not any(k.startswith("clientstore/") for k in run["metrics"][0])


@pytest.mark.parametrize("extra", [
    dict(error_type="local", local_momentum=0.0),   # err bank only
    dict(error_type="none", local_momentum=0.9),    # vel bank only
])
def test_single_bank_modes_match_device(extra):
    dev = _run_store(n_rounds=4, **extra)
    host = _run_store(n_rounds=4, client_store="host", **extra)
    np.testing.assert_array_equal(dev["losses"], host["losses"])
    np.testing.assert_allclose(dev["params"], host["params"], atol=1e-6)
    # exactly the needed bank is hosted
    assert (host["vel"] is None) == (extra["local_momentum"] == 0.0)
    assert (host["err"] is None) == (extra["error_type"] == "none")


# ---------------------------------------------------------------------------
# config validation + deprecation alias
# ---------------------------------------------------------------------------

def test_config_rejects_bad_client_store_combos():
    with pytest.raises(ValueError, match="client_store"):
        Config(**KW, **BASE, client_store="floppy")
    with pytest.raises(ValueError, match="client_store"):
        Config(**KW, **BASE, client_store_cache_rows=4)  # cache needs hosted
    with pytest.raises(ValueError, match="client_store"):
        Config(**KW, **BASE, client_store="host",
               client_store_path="/tmp/x")  # path is mmap-only
    with pytest.raises(ValueError, match="fsdp"):
        Config(**KW, **BASE, client_store="host", fsdp=True)


def test_offload_alias_maps_to_host_store():
    with pytest.warns(DeprecationWarning, match="client_store"):
        cfg = Config(**KW, **BASE, offload_client_state=True)
    assert cfg.client_store == "host" and cfg.client_state_hosted


def test_host_vel_setter_requires_hosted_store():
    cfg = Config(**KW, **BASE)  # device store: no streamer
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    with pytest.raises(ValueError, match="no hosted client store"):
        sess.host_vel = np.zeros((cfg.num_clients, sess.grad_size), np.float32)


# ---------------------------------------------------------------------------
# fedsim masking: dropped clients' hosted rows carry forward untouched
# ---------------------------------------------------------------------------

def test_fedsim_all_dropped_freezes_hosted_banks():
    from tests.test_fedsim import S, _cohort_env

    cfg = Config(**KW, **BASE, client_store="host",
                 availability="bernoulli", dropout_prob=0.5)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    for r in range(2):
        ids, batch = sampler.sample_round(r)
        sess.train_round(ids, batch, 0.3, env=_cohort_env(S))
    vel = np.asarray(sess.host_vel).copy()
    err = np.asarray(sess.host_err).copy()
    before = _final_vec(sess).copy()
    ids, batch = sampler.sample_round(2)
    m = sess.train_round(ids, batch, 0.3, env=_cohort_env([]))
    assert m["fedsim/all_dropped"] == 1.0
    np.testing.assert_array_equal(before, _final_vec(sess))
    np.testing.assert_array_equal(vel, np.asarray(sess.host_vel))
    np.testing.assert_array_equal(err, np.asarray(sess.host_err))
    sess.close_client_store()


# ---------------------------------------------------------------------------
# checkpoint / vault: hosted banks ride the saveable state
# ---------------------------------------------------------------------------

def test_kill_and_resume_hosted_bitwise(tmp_path):
    from commefficient_tpu.utils.checkpoint import FedCheckpointer

    cfg = Config(**KW, **BASE, client_store="host")

    def _train(sess, samp, start, stop, ckpt=None):
        for r in range(start, stop):
            ids, batch = samp.sample_round(r)
            sess.train_round(ids, batch, lr=0.1 + 0.02 * r)
            if ckpt is not None:
                ckpt.maybe_save(sess, r + 1)

    ds, params, loss_fn = _setup(cfg.num_clients)
    sess_a = FederatedSession(cfg, params, loss_fn)
    samp = FedSampler(ds, num_workers=cfg.num_workers,
                      local_batch_size=cfg.local_batch_size, seed=1)
    _train(sess_a, samp, 0, 8)

    ck_cfg = cfg.replace(checkpoint_dir=str(tmp_path / "ck"),
                         checkpoint_every=4)
    sess_b = FederatedSession(ck_cfg, params, loss_fn)
    ckpt = FedCheckpointer(ck_cfg)
    _train(sess_b, samp, 0, 4, ckpt)
    ckpt.close()
    sess_b.close_client_store()

    sess_c = FederatedSession(ck_cfg, params, loss_fn)  # fresh state
    ckpt2 = FedCheckpointer(ck_cfg)
    assert ckpt2.restore(sess_c) == 4
    _train(sess_c, samp, 4, 8)
    ckpt2.close()

    np.testing.assert_array_equal(_final_vec(sess_a), _final_vec(sess_c))
    np.testing.assert_array_equal(sess_a.host_vel, sess_c.host_vel)
    np.testing.assert_array_equal(sess_a.host_err, sess_c.host_err)
    sess_a.close_client_store()
    sess_c.close_client_store()


def test_vault_rollback_hosted_replay_bitwise():
    from commefficient_tpu.resilience import RollbackVault

    cfg = Config(**KW, **BASE, client_store="host")
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    for r in range(3):
        ids, batch = sampler.sample_round(r)
        sess.train_round(ids, batch, 0.3)
    vault = RollbackVault(snapshot_every=3)
    vault.snapshot(sess, 3)
    at3 = _final_vec(sess).copy()
    vel3 = np.asarray(sess.host_vel).copy()

    def two_more():
        for r in range(3, 5):
            ids, batch = sampler.sample_round(r)
            sess.train_round(ids, batch, 0.3)
        return _final_vec(sess).copy(), np.asarray(sess.host_vel).copy()

    first_params, first_vel = two_more()
    assert not np.array_equal(at3, first_params)
    snap = vault.latest(max_step=4)
    assert vault.restore(sess, snap) == 3
    np.testing.assert_array_equal(_final_vec(sess), at3)
    np.testing.assert_array_equal(np.asarray(sess.host_vel), vel3)
    # same hosted program, restored rows -> the replay is bit-identical
    replay_params, replay_vel = two_more()
    np.testing.assert_array_equal(replay_params, first_params)
    np.testing.assert_array_equal(replay_vel, first_vel)
    sess.close_client_store()


# ---------------------------------------------------------------------------
# pipeline: prefetched cohorts (+ staleness regather) stay bit-exact
# ---------------------------------------------------------------------------

def test_pipelined_hosted_bitwise_matches_sync():
    """depth 2 over 12 clients / 8 workers: cohorts collide inside the
    window every round, so this exercises the stale-cohort regather."""
    from commefficient_tpu.pipeline.engine import PipelinedRounds

    # sync twin (plain loop, fixed lr)
    sync = _run_store(n_rounds=6, client_store="host", telemetry_level=0)

    cfg = Config(**{**KW, **BASE}, client_store="host", pipeline_depth=2)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    eng = PipelinedRounds(cfg, sess, sampler, lambda s: 0.3, num_rounds=6,
                          steps_per_epoch=6).start()
    losses = [float(m["loss"]) for _, _, m in eng.epoch_rounds(0, 0)]
    eng.close()
    np.testing.assert_array_equal(np.asarray(losses), sync["losses"][:6])
    np.testing.assert_array_equal(_final_vec(sess), sync["params"])
    np.testing.assert_array_equal(np.asarray(sess.host_vel), sync["vel"])
    assert sess.retrace_sentinel.retraces == 0
    sess.close_client_store()


# ---------------------------------------------------------------------------
# ladder: rung switches under a hosted store retrace nothing
# ---------------------------------------------------------------------------

def test_ladder_rung_switch_hosted_zero_retraces():
    from commefficient_tpu.control import build_controller

    cfg = Config(**BASE, mode="local_topk", error_type="local",
                 local_momentum=0.9, topk_method="threshold",
                 client_store="host", telemetry_level=1,
                 control_policy="fixed", control_schedule="0-1=0,2-=1",
                 ladder="k=30,15")
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ctrl = build_controller(cfg, sess, num_rounds=4)
    ctrl.prewarm(sampler, 0.2)
    for r in range(4):
        ids, batch = sampler.sample_round(r)
        sess.train_round(ids, batch, 0.2)
    assert ctrl.switches == 1 and sess.active_rung == 1
    assert sess.retrace_sentinel.retraces == 0
    assert np.abs(np.asarray(sess.host_vel)).sum() > 0
    sess.close_client_store()


# ---------------------------------------------------------------------------
# the strict W*k audit bound (no writeback exemption when hosted)
# ---------------------------------------------------------------------------

def test_hosted_audit_strict_sparse_bound_no_exemption(tmp_path):
    checker = _checker()
    kw = dict(mode="local_topk", error_type="local", k=7,
              topk_method="threshold", aggregate="sparse")
    cfg = Config(**kw, **BASE, client_store="host")
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    ids, batch = sampler.sample_round(0)
    audit = sess.audit_compiled_round(np.asarray(ids), batch, 0.2)
    rep = audit.report(generated_by="test", cfg=cfg)
    # strict W*k bound, no client_state_writeback inflation
    assert rep["collectives"]["sparse_agg_bound"] == 8 * 7
    assert rep["collectives"]["sparse_agg_exemption"] is None
    ag = rep["collectives"]["max_all_gather_elems"]
    assert ag is None or ag <= 8 * 7
    path = audit.write(str(tmp_path), generated_by="test", cfg=cfg)
    checker.validate_perf_report(path)  # hosted report passes strict
    sess.close_client_store()

    # the device twin still needs (and declares) the exemption
    cfg_d = Config(**kw, **BASE)
    sess_d = FederatedSession(cfg_d, params, loss_fn)
    rep_d = sess_d.audit_compiled_round(
        np.asarray(ids), batch, 0.2).report(generated_by="test", cfg=cfg_d)
    assert rep_d["collectives"]["sparse_agg_exemption"] == \
        "client_state_writeback"
    assert rep_d["collectives"]["sparse_agg_bound"] > 8 * 7

    # checker rejection: a hosted run may NOT carry any exemption
    with open(path) as f:
        rec = json.load(f)
    rec["collectives"]["sparse_agg_exemption"] = "client_state_writeback"
    bad = tmp_path / "bad_perf.json"
    bad.write_text(json.dumps(rec))
    with pytest.raises(checker.SchemaError, match="exemption"):
        checker.validate_perf_report(str(bad))


def test_hosted_round_hlo_has_no_client_bank_operand():
    """The acceptance pin: with a hosted store the compiled round program
    contains no [num_clients, D]-shaped operand at all (the gather/scatter
    moved off-graph); the device round does."""
    import jax.numpy as jnp

    cfg_h = Config(**KW, **BASE, client_store="host")
    cfg_d = Config(**KW, **BASE)
    ds, params, loss_fn = _setup(cfg_h.num_clients)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    ids, batch = sampler.sample_round(0)
    jb = {k: jnp.asarray(v) for k, v in batch.items()}

    sess_h = FederatedSession(cfg_h, params, loss_fn)
    bank_shape = f"tensor<{cfg_h.num_clients}x{sess_h.grad_size}xf32>"
    cohort = sess_h._streamer.gather(np.asarray(ids))
    text_h = sess_h.round_fn.lower(
        sess_h.state, jnp.asarray(ids), jb, jnp.float32(0.2),
        cohort.vel, cohort.err).as_text()
    assert bank_shape not in text_h
    sess_h.close_client_store()

    sess_d = FederatedSession(cfg_d, params, loss_fn)
    text_d = sess_d.round_fn.lower(
        sess_d.state, jnp.asarray(ids), jb, jnp.float32(0.2)).as_text()
    assert bank_shape in text_d


# ---------------------------------------------------------------------------
# scale: C = 1,000,000 on CPU — hosted works where device cannot allocate
# ---------------------------------------------------------------------------

_MILLION_CHILD = textwrap.dedent("""
    import resource, sys
    kind, root = sys.argv[1], sys.argv[2]
    # cap anonymous memory well under the two [1e6, D] f32 banks
    # (~1.7 GB); file-backed mmap pages do not count against RLIMIT_DATA
    LIM = 1_300_000_000
    resource.setrlimit(resource.RLIMIT_DATA, (LIM, LIM))
    try:
        import numpy as np
        import jax, jax.numpy as jnp
        import flax.linen as nn
        from commefficient_tpu.parallel import FederatedSession
        from commefficient_tpu.models.losses import classification_loss
        from commefficient_tpu.utils.config import Config

        class TinyMLP(nn.Module):
            @nn.compact
            def __call__(self, x):
                return nn.Dense(4)(nn.relu(nn.Dense(16)(x)))

        C = 1_000_000
        cfg = Config(mode="local_topk", error_type="local",
                     local_momentum=0.9, k=8, num_clients=C,
                     num_workers=4, num_devices=1, local_batch_size=2,
                     weight_decay=0.0, seed=0, client_store=kind,
                     client_store_path=(root + "/bank" if kind == "mmap"
                                        else ""))
        model = TinyMLP()
        params = model.init(jax.random.key(0), jnp.zeros((1, 8)))
        sess = FederatedSession(cfg, params,
                                classification_loss(model.apply))
        rng = np.random.default_rng(0)
        ids = np.array([3, 999_999, 123_456, 500_000], dtype=np.int32)
        batch = {"x": rng.normal(size=(4, 2, 8)).astype(np.float32),
                 "y": rng.integers(0, 4, size=(4, 2)).astype(np.int32)}
        for _ in range(2):
            m = sess.train_round(ids, batch, 0.1)
        assert np.isfinite(float(m["loss"]))
        # the touched rows really landed in the million-row bank
        rows = sess._streamer.vel_store.gather_rows(ids)
        assert np.abs(rows).sum() > 0
        sess.close_client_store()
        print("OK")
    except Exception as e:
        print(f"{type(e).__name__}: {e}", file=sys.stderr)
        sys.exit(7)
""")


def _run_million(kind, tmp_path):
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS": "--xla_force_host_platform_device_count=1",
           "PYTHONPATH": REPO}
    script = tmp_path / "child.py"
    script.write_text(_MILLION_CHILD)
    return subprocess.run(
        [sys.executable, str(script), kind, str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=300)


def test_million_clients_mmap_succeeds_where_device_cannot(tmp_path):
    """The tentpole's scale claim, machine-checked: under a hard
    RLIMIT_DATA the device store cannot even allocate the [1e6, D] banks,
    while the mmap store trains rounds (its bank is file-backed)."""
    ok = _run_million("mmap", tmp_path)
    assert ok.returncode == 0, ok.stderr[-2000:]
    assert "OK" in ok.stdout
    dev = _run_million("device", tmp_path)
    assert dev.returncode == 7, (dev.returncode, dev.stderr[-2000:])
