"""Child process for tests/test_multihost.py — NOT a test module.

Runs as ``python multihost_child.py <pid> <port>``: joins a 2-process
jax.distributed cluster (4 virtual CPU devices each) through the PUBLIC
multihost bring-up (``multihost.initialize_multihost`` reading
JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID, then
``make_global_mesh`` declaring the (hosts, workers, model, seq) pod
mesh), builds THIS host's topology + data plane, and runs federated
sketch rounds whose table psum crosses the process boundary (Gloo
standing in for DCN). Each process realizes only its own client
partition's batch rows (``HostDataPlane`` + ``assemble_rows``); the
cohort id vector is global (draws are cheap ints, every process computes
every host's).
"""

import os
import sys

pid, port = int(sys.argv[1]), sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(pid)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from commefficient_tpu.utils.platform import force_virtual_cpu_devices  # noqa: E402

force_virtual_cpu_devices(4)

import numpy as np  # noqa: E402

from commefficient_tpu.utils.config import Config  # noqa: E402

cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
             k=8, num_rows=3, num_cols=64, num_clients=16, num_workers=8,
             num_devices=8, local_batch_size=4, weight_decay=0.0,
             num_hosts=2, distributed=True)

from commefficient_tpu.multihost import (  # noqa: E402
    HostDataPlane,
    assemble_rows,
    build_topology,
    global_client_ids,
    initialize_multihost,
    make_global_mesh,
    validate_mesh_topology,
)

assert initialize_multihost(cfg) is True

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import flax.linen as nn  # noqa: E402

assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

mesh = make_global_mesh(cfg)
topology = build_topology(cfg)  # host_id = jax.process_index()
assert topology.host_id == pid
validate_mesh_topology(mesh, topology)

from commefficient_tpu.data import FedDataset  # noqa: E402
from commefficient_tpu.models import classification_loss  # noqa: E402
from commefficient_tpu.parallel import FederatedSession  # noqa: E402


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(4)(nn.tanh(nn.Dense(8)(x)))


model = MLP()
params = model.init(jax.random.key(0), jnp.zeros((1, 6)))
loss_fn = classification_loss(model.apply)
session = FederatedSession(cfg, params, loss_fn, mesh=mesh)

rng = np.random.default_rng(0)  # same seed both processes -> same dataset
x = rng.normal(size=(320, 6)).astype(np.float32)
y = rng.integers(0, 4, size=320).astype(np.int32)
ds = FedDataset({"x": x, "y": y}, cfg.num_clients, iid=True, seed=0)

# every process holds a plane PER HOST for the id draws (cheap ints); only
# its OWN plane realizes batch rows
planes = [
    HostDataPlane(ds, build_topology(cfg, host_id=h),
                  local_batch_size=cfg.local_batch_size, seed=cfg.seed)
    for h in range(cfg.num_hosts)
]
mine = planes[topology.host_id]

loss = None
for r in range(2):
    ids = global_client_ids(planes, r)  # host-major [W], same everywhere
    local_ids, local_batch = mine.sample_round(r)
    np.testing.assert_array_equal(
        ids[topology.slot_range[0]:topology.slot_range[1]], local_ids)
    # lift this host's rows into the global [W, B, ...] arrays — the
    # callback only materializes shards this process addresses, so the
    # other host's rows never exist here
    batch = {
        k: assemble_rows(mesh, {topology.host_id: v},
                         num_hosts=cfg.num_hosts)
        for k, v in local_batch.items()
    }
    m = session.train_round(ids, batch, lr=0.1)
    loss = float(m["loss"])
    assert np.isfinite(loss), loss
print(f"MULTIHOST_OK pid={pid} loss={loss:.6f}")
