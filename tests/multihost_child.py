"""Child process for tests/test_multihost.py — NOT a test module.

Runs as ``python multihost_child.py <pid> <port>``: joins a 2-process
jax.distributed cluster (4 virtual CPU devices each) through the
PUBLIC bring-up path (``parallel.mesh.initialize_distributed`` reading
JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/JAX_PROCESS_ID), then runs one
federated sketch round over the 8-device global mesh — the multi-host
capability SURVEY.md §5 names as the rebuild extension (psum across
processes stands in for DCN).
"""

import os
import sys

pid, port = int(sys.argv[1]), sys.argv[2]
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
os.environ["JAX_NUM_PROCESSES"] = "2"
os.environ["JAX_PROCESS_ID"] = str(pid)
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from commefficient_tpu.utils.platform import force_virtual_cpu_devices  # noqa: E402

force_virtual_cpu_devices(4)

from commefficient_tpu.parallel.mesh import (  # noqa: E402
    initialize_distributed,
    make_mesh,
)

assert initialize_distributed() is True

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import flax.linen as nn  # noqa: E402

assert len(jax.devices()) == 8, jax.devices()
assert len(jax.local_devices()) == 4

from commefficient_tpu.models import classification_loss  # noqa: E402
from commefficient_tpu.parallel import FederatedSession  # noqa: E402
from commefficient_tpu.utils.config import Config  # noqa: E402


class MLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        return nn.Dense(4)(nn.tanh(nn.Dense(8)(x)))


model = MLP()
params = model.init(jax.random.key(0), jnp.zeros((1, 6)))
loss_fn = classification_loss(model.apply)
cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
             k=8, num_rows=3, num_cols=64, num_clients=16, num_workers=8,
             num_devices=8, local_batch_size=4, weight_decay=0.0)
session = FederatedSession(cfg, params, loss_fn, mesh=make_mesh(8))
rng = np.random.default_rng(0)  # same seed both processes -> same batch
ids = rng.choice(16, size=8, replace=False).astype(np.int32)
batch = {"x": rng.normal(size=(8, 4, 6)).astype(np.float32),
         "y": rng.integers(0, 4, size=(8, 4)).astype(np.int32)}
loss = None
for r in range(2):
    m = session.train_round(ids, batch, lr=0.1)
    loss = float(m["loss"])
    assert np.isfinite(loss), loss
print(f"MULTIHOST_OK pid={pid} loss={loss:.6f}")
