"""Round-engine tests on the virtual 8-device CPU mesh.

The strategy SURVEY.md §4 demands: every compression mode is verified on a
fake multi-device mesh against the single-device oracle, and degenerate
settings (k=D, huge sketch, 1 local iter) must reduce exactly/approximately
to the uncompressed path.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.data import FedDataset, FedSampler
from commefficient_tpu.models.losses import classification_loss
from commefficient_tpu.ops import ravel_params
from commefficient_tpu.parallel import FederatedSession, make_mesh
from commefficient_tpu.utils.config import Config


class TinyMLP(nn.Module):
    num_classes: int = 4

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(self.num_classes)(x)


D_IN = 8
N_CLASSES = 4


def _setup(num_clients=12):
    rng = np.random.default_rng(0)
    n = 600
    w = rng.normal(size=(D_IN, N_CLASSES))
    x = rng.normal(size=(n, D_IN)).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.normal(size=(n, N_CLASSES)), axis=1).astype(np.int32)
    ds = FedDataset({"x": x, "y": y}, num_clients, iid=True, seed=0)
    model = TinyMLP()
    params = model.init(jax.random.key(0), jnp.zeros((1, D_IN)))
    loss_fn = classification_loss(model.apply)
    return ds, params, loss_fn


def _run(cfg, n_rounds=5, lr=0.3, fedavg_iters=None):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    losses = []
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        if cfg.mode == "fedavg":
            L = cfg.num_local_iters
            batch = {k: v.reshape(v.shape[0], L, v.shape[1] // L, *v.shape[2:])
                     for k, v in batch.items()}
        m = sess.train_round(ids, batch, lr)
        losses.append(float(m["loss"]))
    return sess, losses


def _final_vec(sess):
    return np.asarray(sess.state.params_vec)


BASE = dict(num_clients=12, num_workers=8, num_devices=8, local_batch_size=4,
            weight_decay=0.0, seed=5)


def test_uncompressed_multidevice_matches_single_device():
    cfg8 = Config(mode="uncompressed", **BASE)
    cfg1 = Config(mode="uncompressed", **{**BASE, "num_devices": 1})
    s8, l8 = _run(cfg8)
    s1, l1 = _run(cfg1)
    np.testing.assert_allclose(l8, l1, rtol=1e-4)
    np.testing.assert_allclose(_final_vec(s8), _final_vec(s1), atol=1e-5)


def test_uncompressed_loss_decreases():
    _, losses = _run(Config(mode="uncompressed", **BASE), n_rounds=12)
    assert losses[-1] < losses[0] * 0.8


def test_true_topk_full_k_equals_uncompressed():
    ds, params, loss_fn = _setup()
    d = ravel_params(params)[0].size
    cfg_t = Config(mode="true_topk", error_type="virtual", k=int(d), **BASE)
    cfg_u = Config(mode="uncompressed", **BASE)
    st, _ = _run(cfg_t)
    su, _ = _run(cfg_u)
    np.testing.assert_allclose(_final_vec(st), _final_vec(su), atol=1e-5)


def test_local_topk_full_k_equals_uncompressed():
    ds, params, loss_fn = _setup()
    d = ravel_params(params)[0].size
    cfg_t = Config(mode="local_topk", error_type="local", k=int(d), **BASE)
    cfg_u = Config(mode="uncompressed", **BASE)
    st, _ = _run(cfg_t)
    su, _ = _run(cfg_u)
    np.testing.assert_allclose(_final_vec(st), _final_vec(su), atol=1e-5)


def test_local_topk_no_error_full_k_equals_uncompressed():
    """local_topk without error feedback transmits gradient-scale values and
    the server applies lr exactly once (regression: no double lr scaling)."""
    ds, params, loss_fn = _setup()
    d = ravel_params(params)[0].size
    cfg_t = Config(mode="local_topk", error_type="none", k=int(d), **BASE)
    cfg_u = Config(mode="uncompressed", **BASE)
    st, _ = _run(cfg_t)
    su, _ = _run(cfg_u)
    np.testing.assert_allclose(_final_vec(st), _final_vec(su), atol=1e-5)


def test_envelope_warning_suggestion_converges():
    """The d/c envelope warning's 'Raise num_cols to >=' advice must
    actually clear the realized-width check when followed (review r4: a
    requested-space suggestion can realize below the target)."""
    import re
    import warnings as _w

    import flax.linen as nn

    class Wide(nn.Module):  # d ~ 2.3M: realized widths track requests
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.Dense(8192)(x))

    m = Wide()
    params = m.init(jax.random.key(0), jnp.zeros((1, 256)))
    loss_fn = classification_loss(m.apply)
    kw = dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
              k=16, num_rows=3, **{**BASE, "num_devices": 1})

    def build(num_cols):
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            FederatedSession(Config(num_cols=num_cols, **kw), params, loss_fn)
            return [str(x.message) for x in rec if "envelope" in str(x.message)]

    first = build(20_000)  # d/c ~ 100: far outside the envelope
    assert first, "expected the envelope warning to fire"
    suggest = int(
        re.search(r"Raise num_cols to >= ([\d,]+)", first[0])
        .group(1).replace(",", "")
    )
    assert not build(suggest), "following the suggestion must clear the check"


def test_envelope_predictor_matches_measured_cliffs():
    """The fitted error-bank model (parallel/envelope.py) must reproduce
    the r4 sweep's three measured cliff locations (runs/r4_envelope.log)
    and stay monotone in gamma — the r5 replacement for the hard-coded
    d > 25*c check (VERDICT r4 item 6)."""
    from commefficient_tpu.parallel.envelope import (
        predicted_dc_max,
        stable_dc_bound,
    )

    # gamma=1: cliff measured between 25 (trains) and 30 (chance)
    assert 25 < predicted_dc_max(1.0) < 30
    # gamma=0.95: 35 partial / 40 broken
    assert 33 < predicted_dc_max(0.95) < 40
    # gamma=0.9: 40 trains fully / 50 partial
    assert 40 < predicted_dc_max(0.9) < 50
    # lower decay -> strictly wider envelope
    gammas = [1.0, 0.95, 0.9, 0.85, 0.8]
    preds = [predicted_dc_max(g) for g in gammas]
    assert preds == sorted(preds) and len(set(preds)) == len(preds)
    # the runtime bound is conservative: below the fitted cliff everywhere
    for g in gammas:
        assert stable_dc_bound(g) < predicted_dc_max(g)


def test_envelope_warning_gamma_dependent():
    """error_decay widens the runtime envelope: a d/c that warns undecayed
    must pass the check at gamma=0.9 (fitted bound ~41.7 vs 23.1)."""
    import warnings as _w

    import flax.linen as nn

    class Wide(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.Dense(8192)(x))

    m = Wide()
    params = m.init(jax.random.key(0), jnp.zeros((1, 256)))
    loss_fn = classification_loss(m.apply)
    d = ravel_params(params)[0].size
    kw = dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
              k=16, num_rows=3, **{**BASE, "num_devices": 1})

    def build(error_decay):
        with _w.catch_warnings(record=True) as rec:
            _w.simplefilter("always")
            FederatedSession(
                Config(num_cols=int(d / 30), error_decay=error_decay, **kw),
                params, loss_fn,
            )
            return [str(x.message) for x in rec if "envelope" in str(x.message)]

    assert build(1.0), "d/c ~30 undecayed must warn (cliff ~27)"
    assert not build(0.9), "d/c ~30 at gamma=0.9 is inside the fitted bound"


def test_error_decay_zero_matches_no_error_sketch():
    """error_decay (the r4 d/c-envelope mitigation knob) at gamma=0 drops
    the whole carried error each round, which must reduce the virtual-error
    sketch to the no-error sketch path: top-k selection is scale-invariant
    and estimates are linear, so extracting from lr*m == lr * extracting
    from m."""
    kw = dict(mode="sketch", virtual_momentum=0.9, k=40, num_rows=3,
              num_cols=120, topk_method="threshold", **BASE)
    s_dec, l_dec = _run(Config(error_type="virtual", error_decay=0.0, **kw))
    s_none, l_none = _run(Config(error_type="none", **kw))
    np.testing.assert_allclose(l_dec, l_none, rtol=1e-4)
    np.testing.assert_allclose(_final_vec(s_dec), _final_vec(s_none), atol=1e-5)


def test_error_decay_shrinks_error_bank():
    kw = dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
              k=40, num_rows=3, num_cols=120, topk_method="threshold", **BASE)
    s_full, _ = _run(Config(**kw), n_rounds=6)
    s_dec, losses = _run(Config(error_decay=0.8, **kw), n_rounds=6)
    assert np.all(np.isfinite(losses))
    n_full = float(np.linalg.norm(np.asarray(s_full.state.error)))
    n_dec = float(np.linalg.norm(np.asarray(s_dec.state.error)))
    assert n_dec < n_full


def test_fedavg_one_iter_equals_uncompressed():
    cfg_f = Config(mode="fedavg", num_local_iters=1, local_lr=0.1, **BASE)
    cfg_u = Config(mode="uncompressed", **BASE)
    sf, _ = _run(cfg_f, fedavg_iters=1)
    su, _ = _run(cfg_u)
    np.testing.assert_allclose(_final_vec(sf), _final_vec(su), atol=1e-5)


def test_fedavg_multi_iter_loss_decreases():
    cfg = Config(mode="fedavg", num_local_iters=4, local_lr=0.05,
                 **{**BASE, "local_batch_size": 8})
    _, losses = _run(cfg, n_rounds=10, lr=0.05)
    assert losses[-1] < losses[0] * 0.9


def test_sketch_mode_trains_and_error_feedback_helps():
    # modest sketch: still enough capacity that training converges
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=60, num_rows=5, num_cols=512, **BASE)
    _, losses = _run(cfg, n_rounds=15)
    assert losses[-1] < losses[0] * 0.9


def test_true_topk_sparse_with_error_feedback_trains():
    cfg = Config(mode="true_topk", error_type="virtual", k=40,
                 virtual_momentum=0.9, **BASE)
    _, losses = _run(cfg, n_rounds=15)
    assert losses[-1] < losses[0] * 0.9


def test_local_momentum_state_only_updates_participants():
    cfg = Config(mode="local_topk", error_type="local", k=20,
                 local_momentum=0.9, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    ids, batch = sampler.sample_round(0)
    sess.train_round(ids, batch, 0.1)
    vel = np.asarray(sess.state.client_vel)
    err = np.asarray(sess.state.client_err)
    participated = np.zeros(cfg.num_clients, bool)
    participated[ids] = True
    assert np.abs(vel[participated]).sum() > 0
    assert np.abs(vel[~participated]).sum() == 0
    assert np.abs(err[participated]).sum() > 0
    assert np.abs(err[~participated]).sum() == 0


def test_eval_masks_padded_rows():
    ds, params, loss_fn = _setup()
    cfg = Config(mode="uncompressed", **BASE)
    sess = FederatedSession(cfg, params, loss_fn)
    test_ds = FedDataset(
        {"x": ds.data["x"][:10], "y": ds.data["y"][:10]}, 1, seed=0
    )
    out = sess.evaluate(test_ds.eval_batches(batch_size=8))  # 8 + pad(2->8)
    assert 0.0 <= out["accuracy"] <= 1.0
    assert np.isfinite(out["loss"])


def test_local_topk_with_virtual_momentum_trains():
    # regression: momentum must be allocated for dense modes beyond true_topk
    cfg = Config(mode="local_topk", error_type="local", k=30,
                 virtual_momentum=0.9, **BASE)
    _, losses = _run(cfg, n_rounds=10, lr=0.1)
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_sketch_momentum_dampening_zeroes_hh_coords():
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 momentum_dampening=True, k=40, num_rows=5, num_cols=1024,
                 # parity-experiment path, gated since r3 (VERDICT item 9)
                 allow_unstable_sketch_dampening=True, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    from commefficient_tpu.ops import estimate_all
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    ids, batch = sampler.sample_round(0)
    sess.train_round(ids, batch, 0.2)
    # after the round, the momentum sketch's estimates at the transmitted HH
    # coords must be ~0 (they were subtracted via linearity)
    update_coords = np.asarray(sess.state.params_vec) != np.asarray(
        ravel_params(params)[0]
    )
    est = np.asarray(estimate_all(sess.spec, sess.state.momentum))
    hh_est = est[update_coords]
    assert np.abs(hh_est).max() < 1e-4


def _ignore_batch_like(batch):
    """A batch whose labels are all IGNORE_INDEX -> zero loss, zero grads.
    Round math then isolates the error-feedback residual: the only applied
    update is what was BANKED in earlier rounds."""
    from commefficient_tpu.models.losses import IGNORE_INDEX

    return {**batch, "y": np.full_like(batch["y"], IGNORE_INDEX)}


@pytest.mark.parametrize("mode,extra", [
    ("true_topk", {}),
    ("sketch", dict(num_rows=5, num_cols=512)),
])
def test_error_feedback_banks_lr_at_accumulation(mode, extra):
    """FetchSGD Alg. 1 semantics (round.py docstring DECISION): residual
    error banked at round-1's lr must be applied at THAT lr — round 2's lr
    must not rescale it. Round 2 has zero gradient (all-ignored labels), so
    its applied update is purely the banked residual; changing round-2's lr
    must not change the final params."""
    cfg = Config(mode=mode, error_type="virtual", k=5, **extra, **BASE)
    finals = []
    for lr2 in (0.01, 1.0):
        ds, params, loss_fn = _setup(cfg.num_clients)
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=cfg.num_workers,
                             local_batch_size=cfg.local_batch_size, seed=1)
        ids, batch = sampler.sample_round(0)
        sess.train_round(ids, batch, lr=0.3)
        sess.train_round(ids, _ignore_batch_like(batch), lr=lr2)
        finals.append(_final_vec(sess))
    np.testing.assert_allclose(finals[0], finals[1], atol=1e-6)
    # and the residual really was applied (round 2 changed the params)
    ds, params, _ = _setup(cfg.num_clients)


def test_local_error_banks_lr_at_accumulation():
    """Same contract for per-client (local) error feedback in local_topk."""
    cfg = Config(mode="local_topk", error_type="local", k=5, **BASE)
    finals = []
    for lr2 in (0.01, 1.0):
        ds, params, loss_fn = _setup(cfg.num_clients)
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=cfg.num_workers,
                             local_batch_size=cfg.local_batch_size, seed=1)
        ids, batch = sampler.sample_round(0)
        sess.train_round(ids, batch, lr=0.3)
        sess.train_round(ids, _ignore_batch_like(batch), lr=lr2)
        finals.append(_final_vec(sess))
    np.testing.assert_allclose(finals[0], finals[1], atol=1e-6)


def test_fedavg_matches_weight_average_oracle():
    """With local_lr=None the applied delta is EXACTLY the averaged local
    weight delta (true FedAvg) — oracle-simulated per client in numpy/jax."""
    L, lr = 3, 0.2
    cfg = Config(mode="fedavg", num_local_iters=L,
                 **{**BASE, "local_batch_size": 4})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size * L, seed=1)
    ids, batch = sampler.sample_round(0)
    shaped = {k: v.reshape(v.shape[0], L, v.shape[1] // L, *v.shape[2:])
              for k, v in batch.items()}
    sess.train_round(ids, shaped, lr)

    from commefficient_tpu.ops import ravel_params
    vec0, unravel = ravel_params(params)
    grad_fn = jax.jit(jax.grad(lambda p, b: loss_fn(p, b)[0]))
    finals = []
    for w in range(cfg.num_workers):
        p = np.asarray(vec0, np.float64).copy()
        for step in range(L):
            mb = {k: jnp.asarray(v[w, step]) for k, v in shaped.items()}
            g, _ = jax.flatten_util.ravel_pytree(grad_fn(unravel(jnp.asarray(p, jnp.float32)), mb))
            p = p - lr * np.asarray(g, np.float64)
        finals.append(p)
    oracle = np.mean(finals, axis=0)
    np.testing.assert_allclose(_final_vec(sess), oracle, atol=2e-5)


def test_do_topk_down_sparsifies_the_applied_update():
    """do_topk_down: the broadcast (applied) delta has at most k nonzeros,
    even when the aggregated update is dense."""
    k = 10
    cfg = Config(mode="uncompressed", do_topk_down=True, k=k, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ids, batch = sampler.sample_round(0)
    vec0 = _final_vec(sess).copy()
    sess.train_round(ids, batch, lr=0.3)
    changed = np.sum(_final_vec(sess) != vec0)
    assert 0 < changed <= k
    # accounting matches: download is 2k floats when the flag is set
    assert sess.bytes_per_round()["download_floats"] == 2 * k


def test_weight_decay_round_matches_manual():
    """grad_one's decay path (VERDICT r1 weak 7): one uncompressed round with
    weight_decay equals p - lr*(g + wd*p) computed by hand."""
    wd, lr = 0.1, 0.25
    cfg = Config(mode="uncompressed", num_clients=1, num_workers=1,
                 num_devices=1, local_batch_size=8, weight_decay=wd, seed=5)
    ds, params, loss_fn = _setup(num_clients=1)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=1, local_batch_size=8, seed=1)
    ids, batch = sampler.sample_round(0)
    from commefficient_tpu.ops import ravel_params
    vec0, unravel = ravel_params(params)
    mb = {k: jnp.asarray(v[0]) for k, v in batch.items()}
    g, _ = jax.flatten_util.ravel_pytree(
        jax.grad(lambda p, b: loss_fn(p, b)[0])(params, mb)
    )
    expected = np.asarray(vec0) - lr * (np.asarray(g) + wd * np.asarray(vec0))
    sess.train_round(ids, batch, lr)
    np.testing.assert_allclose(_final_vec(sess), expected, atol=1e-6)


def test_offloaded_client_state_matches_hbm_resident():
    """offload_client_state is a memory placement knob, not a semantics knob:
    multi-round local_topk(+momentum,+error) runs must match exactly."""
    base = Config(mode="local_topk", error_type="local", k=20,
                  local_momentum=0.9, **BASE)
    finals = []
    for offload in (False, True):
        cfg = base.replace(offload_client_state=offload)
        sess, _ = _run(cfg, n_rounds=6)
        finals.append(_final_vec(sess))
        if offload:
            assert sess.state.client_vel == ()
            assert sess.host_vel is not None and np.abs(sess.host_vel).sum() > 0
    np.testing.assert_allclose(finals[0], finals[1], atol=1e-6)


@pytest.mark.parametrize("mode,extra", [
    ("uncompressed", {}),
    ("sketch", dict(error_type="virtual", virtual_momentum=0.9, k=60,
                    num_rows=5, num_cols=512)),
])
def test_fuse_clients_matches_per_client_path(mode, extra):
    """The fused flattened-batch gradient (TPU fast path) is numerically the
    per-client vmap path when nothing per-client is configured."""
    cfg_a = Config(mode=mode, **extra, **BASE)
    cfg_b = cfg_a.replace(fuse_clients=True)
    sa, la = _run(cfg_a, n_rounds=5)
    sb, lb = _run(cfg_b, n_rounds=5)
    np.testing.assert_allclose(la, lb, rtol=1e-5)
    np.testing.assert_allclose(_final_vec(sa), _final_vec(sb), atol=2e-5)


def test_threshold_topk_matches_exact():
    """The binary-searched threshold kernel selects the same coordinates as
    lax.top_k on a tie-free vector (the TPU fast path's contract)."""
    from commefficient_tpu.ops.topk import topk_dense, topk_threshold_dense

    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.normal(size=10_000).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(topk_dense(v, 100)), np.asarray(topk_threshold_dense(v, 100))
    )
    # all-zero input selects nothing
    assert np.asarray(topk_threshold_dense(jnp.zeros(64), 5)).sum() == 0
    # degenerate >k-ties-at-max input still honors the at-most-k contract
    ties = jnp.concatenate([jnp.full(8, 3.0), jnp.arange(8.0)])
    out = np.asarray(topk_threshold_dense(ties, 5))
    assert np.count_nonzero(out) <= 5


def test_fedavg_final_round_at_zero_lr_is_finite():
    """Regression: local_lr=None + the schedule's exact-0 final lr must not
    produce 0/0 = NaN deltas (review finding r2)."""
    cfg = Config(mode="fedavg", num_local_iters=2,
                 **{**BASE, "local_batch_size": 4})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size * 2, seed=1)
    ids, batch = sampler.sample_round(0)
    shaped = {k: v.reshape(v.shape[0], 2, v.shape[1] // 2, *v.shape[2:])
              for k, v in batch.items()}
    before = _final_vec(sess).copy()
    m = sess.train_round(ids, shaped, lr=0.0)
    assert np.isfinite(float(m["loss"]))
    after = _final_vec(sess)
    assert np.isfinite(after).all()
    np.testing.assert_allclose(after, before, atol=1e-7)  # lr 0 => no step


def test_sketch_mode_threshold_topk_trains():
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 topk_method="threshold", k=60, num_rows=5, num_cols=512, **BASE)
    _, losses = _run(cfg, n_rounds=15)
    assert losses[-1] < losses[0] * 0.9


def test_invalid_mode_error_combination_rejected():
    with pytest.raises(NotImplementedError):
        ds, params, loss_fn = _setup()
        FederatedSession(
            Config(mode="sketch", error_type="local", **BASE), params, loss_fn
        )
