"""Round-engine tests on the virtual 8-device CPU mesh.

The strategy SURVEY.md §4 demands: every compression mode is verified on a
fake multi-device mesh against the single-device oracle, and degenerate
settings (k=D, huge sketch, 1 local iter) must reduce exactly/approximately
to the uncompressed path.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.data import FedDataset, FedSampler
from commefficient_tpu.models.losses import classification_loss
from commefficient_tpu.ops import ravel_params
from commefficient_tpu.parallel import FederatedSession, make_mesh
from commefficient_tpu.utils.config import Config


class TinyMLP(nn.Module):
    num_classes: int = 4

    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(self.num_classes)(x)


D_IN = 8
N_CLASSES = 4


def _setup(num_clients=12):
    rng = np.random.default_rng(0)
    n = 600
    w = rng.normal(size=(D_IN, N_CLASSES))
    x = rng.normal(size=(n, D_IN)).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.normal(size=(n, N_CLASSES)), axis=1).astype(np.int32)
    ds = FedDataset({"x": x, "y": y}, num_clients, iid=True, seed=0)
    model = TinyMLP()
    params = model.init(jax.random.key(0), jnp.zeros((1, D_IN)))
    loss_fn = classification_loss(model.apply)
    return ds, params, loss_fn


def _run(cfg, n_rounds=5, lr=0.3, fedavg_iters=None):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    losses = []
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        if cfg.mode == "fedavg":
            L = cfg.num_local_iters
            batch = {k: v.reshape(v.shape[0], L, v.shape[1] // L, *v.shape[2:])
                     for k, v in batch.items()}
        m = sess.train_round(ids, batch, lr)
        losses.append(float(m["loss"]))
    return sess, losses


def _final_vec(sess):
    return np.asarray(sess.state.params_vec)


BASE = dict(num_clients=12, num_workers=8, num_devices=8, local_batch_size=4,
            weight_decay=0.0, seed=5)


def test_uncompressed_multidevice_matches_single_device():
    cfg8 = Config(mode="uncompressed", **BASE)
    cfg1 = Config(mode="uncompressed", **{**BASE, "num_devices": 1})
    s8, l8 = _run(cfg8)
    s1, l1 = _run(cfg1)
    np.testing.assert_allclose(l8, l1, rtol=1e-4)
    np.testing.assert_allclose(_final_vec(s8), _final_vec(s1), atol=1e-5)


def test_uncompressed_loss_decreases():
    _, losses = _run(Config(mode="uncompressed", **BASE), n_rounds=12)
    assert losses[-1] < losses[0] * 0.8


def test_true_topk_full_k_equals_uncompressed():
    ds, params, loss_fn = _setup()
    d = ravel_params(params)[0].size
    cfg_t = Config(mode="true_topk", error_type="virtual", k=int(d), **BASE)
    cfg_u = Config(mode="uncompressed", **BASE)
    st, _ = _run(cfg_t)
    su, _ = _run(cfg_u)
    np.testing.assert_allclose(_final_vec(st), _final_vec(su), atol=1e-5)


def test_local_topk_full_k_equals_uncompressed():
    ds, params, loss_fn = _setup()
    d = ravel_params(params)[0].size
    cfg_t = Config(mode="local_topk", error_type="local", k=int(d), **BASE)
    cfg_u = Config(mode="uncompressed", **BASE)
    st, _ = _run(cfg_t)
    su, _ = _run(cfg_u)
    np.testing.assert_allclose(_final_vec(st), _final_vec(su), atol=1e-5)


def test_fedavg_one_iter_equals_uncompressed():
    cfg_f = Config(mode="fedavg", num_local_iters=1, local_lr=0.1, **BASE)
    cfg_u = Config(mode="uncompressed", **BASE)
    sf, _ = _run(cfg_f, fedavg_iters=1)
    su, _ = _run(cfg_u)
    np.testing.assert_allclose(_final_vec(sf), _final_vec(su), atol=1e-5)


def test_fedavg_multi_iter_loss_decreases():
    cfg = Config(mode="fedavg", num_local_iters=4, local_lr=0.05,
                 **{**BASE, "local_batch_size": 8})
    _, losses = _run(cfg, n_rounds=10, lr=0.05)
    assert losses[-1] < losses[0] * 0.9


def test_sketch_mode_trains_and_error_feedback_helps():
    # modest sketch: still enough capacity that training converges
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=60, num_rows=5, num_cols=512, **BASE)
    _, losses = _run(cfg, n_rounds=15)
    assert losses[-1] < losses[0] * 0.9


def test_true_topk_sparse_with_error_feedback_trains():
    cfg = Config(mode="true_topk", error_type="virtual", k=40,
                 virtual_momentum=0.9, **BASE)
    _, losses = _run(cfg, n_rounds=15)
    assert losses[-1] < losses[0] * 0.9


def test_local_momentum_state_only_updates_participants():
    cfg = Config(mode="local_topk", error_type="local", k=20,
                 local_momentum=0.9, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    ids, batch = sampler.sample_round(0)
    sess.train_round(ids, batch, 0.1)
    vel = np.asarray(sess.state.client_vel)
    err = np.asarray(sess.state.client_err)
    participated = np.zeros(cfg.num_clients, bool)
    participated[ids] = True
    assert np.abs(vel[participated]).sum() > 0
    assert np.abs(vel[~participated]).sum() == 0
    assert np.abs(err[participated]).sum() > 0
    assert np.abs(err[~participated]).sum() == 0


def test_eval_masks_padded_rows():
    ds, params, loss_fn = _setup()
    cfg = Config(mode="uncompressed", **BASE)
    sess = FederatedSession(cfg, params, loss_fn)
    test_ds = FedDataset(
        {"x": ds.data["x"][:10], "y": ds.data["y"][:10]}, 1, seed=0
    )
    out = sess.evaluate(test_ds.eval_batches(batch_size=8))  # 8 + pad(2->8)
    assert 0.0 <= out["accuracy"] <= 1.0
    assert np.isfinite(out["loss"])


def test_local_topk_with_virtual_momentum_trains():
    # regression: momentum must be allocated for dense modes beyond true_topk
    cfg = Config(mode="local_topk", error_type="local", k=30,
                 virtual_momentum=0.9, **BASE)
    _, losses = _run(cfg, n_rounds=10, lr=0.1)
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_sketch_momentum_dampening_zeroes_hh_coords():
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 momentum_dampening=True, k=40, num_rows=5, num_cols=1024, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    from commefficient_tpu.ops import estimate_all
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    ids, batch = sampler.sample_round(0)
    sess.train_round(ids, batch, 0.2)
    # after the round, the momentum sketch's estimates at the transmitted HH
    # coords must be ~0 (they were subtracted via linearity)
    update_coords = np.asarray(sess.state.params_vec) != np.asarray(
        ravel_params(params)[0]
    )
    est = np.asarray(estimate_all(sess.spec, sess.state.momentum))
    hh_est = est[update_coords]
    assert np.abs(hh_est).max() < 1e-4


def test_invalid_mode_error_combination_rejected():
    with pytest.raises(NotImplementedError):
        ds, params, loss_fn = _setup()
        FederatedSession(
            Config(mode="sketch", error_type="local", **BASE), params, loss_fn
        )
