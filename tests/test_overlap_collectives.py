"""Layer-wise collective overlap (ISSUE 16): chunked exchanges, bit-equal.

``--overlap_collectives layerwise`` splits the round's aggregation
collectives — the sketch-table psum and the top-k modes' pair all_gather
— into per-leaf-group / per-segment collectives the latency-hiding
scheduler can issue as the backward produces them. The knob is a pure
scheduling choice, so the contract pinned here is equality, not speed
(the speed side is bench.py's ``sketch_overlap_layerwise`` leg):

  * ops level, on the real 8-device mesh: ``psum_segments`` is BIT-equal
    to one psum of the concatenated segments (``psum_segments_fused``),
    and the chunked ``all_gather_pairs`` rebuilds the monolithic layout
    byte for byte — an all-reduce is elementwise and a gather is pure
    data movement, so segmentation changes which collective carries an
    element, never its value;
  * round level: layerwise-vs-none final params and per-round losses are
    BIT-equal for every sparse-exchange mode (local_topk/local,
    true_topk/virtual, sketch on the sharded decode), including under
    fedsim availability masking;
  * the sketch-FUSED-backward layerwise round regroups the per-leaf
    cotangent fan-in (per-GROUP tables), so it is pinned at the fused
    backward's own tolerance class (PR-12: atol 5e-5 * scale; measured
    ~3e-8) and composes with bf16 tables;
  * ``overlap_collectives='none'`` (the default) lowers BYTE-identical
    HLO — the golden registry parity stays untouched by construction;
  * the layerwise fused round carries the ``overlap_layerwise_psum``
    scope so profiles attribute the segmented collectives;
  * config rejections: unknown overlap value; ``async_double_buffer``
    without the asyncfed engine (the deferred fence needs cohort
    launches to hide behind).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from test_round import BASE, _final_vec, _run, _setup

from commefficient_tpu.data import FedSampler
from commefficient_tpu.ops.collectives import (
    all_gather_pairs,
    psum_segments,
    psum_segments_fused,
)
from commefficient_tpu.ops.collectives.sparse_allreduce import _segment_bounds
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.parallel.mesh import WORKERS, make_mesh
from commefficient_tpu.parallel.round import leaf_groups
from commefficient_tpu.utils.config import Config
from commefficient_tpu.utils.jax_compat import shard_map

P = jax.sharding.PartitionSpec
Wd = 8


# ---------------------------------------------------------------------------
# segment bookkeeping
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("n,segments", [(1, 4), (3, 4), (4, 4), (17, 4),
                                        (100, 1), (100, 7)])
def test_segment_bounds_cover_exactly_once(n, segments):
    bounds = _segment_bounds(n, segments)
    assert bounds[0][0] == 0 and bounds[-1][1] == n
    for (a, b), (a2, _) in zip(bounds, bounds[1:]):
        assert b == a2
    assert all(b > a for a, b in bounds)  # every chunk non-empty
    assert len(bounds) <= max(1, min(segments, n))


@pytest.mark.parametrize("sizes,segments", [
    ([10, 10, 10, 10], 4),
    ([1, 1, 1], 8),          # fewer leaves than segments
    ([100, 1, 1, 1, 1], 3),  # one dominant leaf
    ([5], 4),
    (list(range(1, 20)), 4),
])
def test_leaf_groups_cover_contiguously(sizes, segments):
    bounds = leaf_groups(sizes, segments)
    assert bounds[0][0] == 0 and bounds[-1][1] == len(sizes)
    for (a, b), (a2, _) in zip(bounds, bounds[1:]):
        assert b == a2
    assert all(b > a for a, b in bounds)  # non-empty groups
    assert len(bounds) <= max(1, min(segments, len(sizes)))


# ---------------------------------------------------------------------------
# ops level: the segmented collectives on the real mesh
# ---------------------------------------------------------------------------

def test_psum_segments_bit_equal_to_fused_psum_on_mesh():
    """The claim in one op: per-segment psums == one psum of the
    concatenated segments, element for element (np.array_equal)."""
    rng = np.random.default_rng(3)
    # deliberately ragged shapes; psum_segments_fused flattens+concats
    shapes = [(13,), (4, 7), (31,), (2, 3, 5)]
    xs = [jnp.asarray(rng.normal(size=(Wd,) + s).astype(np.float32) * 100)
          for s in shapes]
    mesh = make_mesh(Wd)

    def body(*segs):
        segs = tuple(s.reshape(s.shape[1:]) for s in segs)
        a = psum_segments(segs, WORKERS)
        b = psum_segments_fused(segs, WORKERS)
        return tuple(x[None] for x in a), tuple(x[None] for x in b)

    f = shard_map(body, mesh=mesh,
                  in_specs=tuple(P(WORKERS) for _ in xs),
                  out_specs=(tuple(P(WORKERS) for _ in xs),
                             tuple(P(WORKERS) for _ in xs)))
    seg_out, fused_out = jax.jit(f)(*xs)
    for a, b in zip(seg_out, fused_out):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("kb,segments", [(11, 4), (3, 4), (1, 4), (64, 2)])
def test_all_gather_pairs_chunked_rebuilds_monolithic(kb, segments):
    """Chunked gathers concatenated along the pair axis == the single
    monolithic gather, byte for byte (pure data movement)."""
    rng = np.random.default_rng(7)
    idx = jnp.asarray(rng.integers(0, 1000, size=(Wd, kb)).astype(np.int32))
    val = jnp.asarray(rng.normal(size=(Wd, kb)).astype(np.float32))
    mesh = make_mesh(Wd)

    def body(i, v):
        i, v = i.reshape(-1), v.reshape(-1)
        gi_m, gv_m = all_gather_pairs(i, v, WORKERS)
        gi_s, gv_s = all_gather_pairs(i, v, WORKERS, segments=segments)
        return gi_m[None], gv_m[None], gi_s[None], gv_s[None]

    f = shard_map(body, mesh=mesh, in_specs=(P(WORKERS), P(WORKERS)),
                  out_specs=(P(WORKERS),) * 4)
    gi_m, gv_m, gi_s, gv_s = jax.jit(f)(idx, val)
    np.testing.assert_array_equal(np.asarray(gi_m), np.asarray(gi_s))
    np.testing.assert_array_equal(np.asarray(gv_m), np.asarray(gv_s))


# ---------------------------------------------------------------------------
# round level: layerwise == none, bit for bit, per sparse mode
# ---------------------------------------------------------------------------

SPARSE_MODES = {
    "local_topk": dict(mode="local_topk", error_type="local", k=7,
                       topk_method="threshold", aggregate="sparse"),
    "true_topk": dict(mode="true_topk", error_type="virtual",
                      virtual_momentum=0.9, k=9, topk_method="threshold",
                      aggregate="sparse"),
    "sketch": dict(mode="sketch", error_type="virtual",
                   virtual_momentum=0.9, k=40, num_rows=3, num_cols=256,
                   topk_method="threshold", aggregate="sparse"),
}


# Only the headline sketch mode stays in the default tier — the other two
# sparse modes exercise the identical chunked-exchange code path and ride
# the slow tier (PR-12 precedent: keep one default-tier pin per claim).
@pytest.mark.parametrize(
    "mode_kw",
    [pytest.param(kw, id=name,
                  marks=() if name == "sketch" else (pytest.mark.slow,))
     for name, kw in SPARSE_MODES.items()],
)
def test_layerwise_bit_equal_to_none_sparse_modes(mode_kw):
    """Same rounds, same data: chunking the pair gathers must not move a
    single bit — params AND every drained loss scalar."""
    s_none, l_none = _run(Config(overlap_collectives="none",
                                 **mode_kw, **BASE))
    s_lw, l_lw = _run(Config(overlap_collectives="layerwise",
                             **mode_kw, **BASE))
    assert l_lw == l_none  # exact float equality, round by round
    np.testing.assert_array_equal(_final_vec(s_lw), _final_vec(s_none))


@pytest.mark.slow
def test_layerwise_bit_equal_under_fedsim_masking():
    """Availability masking is pre-encode; it must commute with the
    chunked exchange exactly as it does with the monolithic one."""
    from test_sketch_decode import _cohort_env

    def masked(ov):
        cfg = Config(availability="bernoulli", dropout_prob=0.5,
                     overlap_collectives=ov,
                     **SPARSE_MODES["local_topk"], **BASE)
        ds, params, loss_fn = _setup(cfg.num_clients)
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
        losses = []
        for r in range(3):
            ids, batch = sampler.sample_round(r)
            m = sess.train_round(ids, batch, 0.3,
                                 env=_cohort_env([0, 2, 3, 5, 7]))
            losses.append(float(m["loss"]))
        return sess, losses

    s_none, l_none = masked("none")
    s_lw, l_lw = masked("layerwise")
    assert l_lw == l_none
    np.testing.assert_array_equal(_final_vec(s_lw), _final_vec(s_none))


# ---------------------------------------------------------------------------
# sketch fused backward: per-GROUP tables, fused-bwd tolerance class
# ---------------------------------------------------------------------------

def _fused_cfg(**kw):
    return Config(**{**BASE, "mode": "sketch", "error_type": "virtual",
                     "virtual_momentum": 0.9, "k": 40, "num_rows": 3,
                     "num_cols": 256, "topk_method": "threshold",
                     "fuse_clients": True, "weight_decay": 1e-4,
                     "sketch_fused_bwd": True, **kw})


def _run_fused(cfg, n_rounds=4):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, 0.2)
    return sess, float(np.asarray(m["loss"]))


def test_fused_bwd_layerwise_parity_with_monolithic():
    """Per-leaf-GROUP tables reorder the cotangent fan-in into the
    table, so layerwise-vs-none here is the fused backward's OWN
    tolerance class (PR-12: atol 5e-5 * scale), not bit-equality."""
    s_none, l_none = _run_fused(_fused_cfg())
    s_lw, l_lw = _run_fused(_fused_cfg(overlap_collectives="layerwise"))
    p_n = np.asarray(s_none.state.params_vec)
    p_l = np.asarray(s_lw.state.params_vec)
    scale = max(np.abs(p_n).max(), 1.0)
    np.testing.assert_allclose(p_l, p_n, rtol=0, atol=5e-5 * scale)
    assert abs(l_lw - l_none) < 1e-3


@pytest.mark.slow
def test_fused_bwd_layerwise_composes_with_bf16_tables():
    s_lw, loss = _run_fused(_fused_cfg(overlap_collectives="layerwise",
                                       sketch_table_dtype="bfloat16"))
    assert np.isfinite(loss)
    assert s_lw.state.momentum.dtype == jnp.bfloat16


# ---------------------------------------------------------------------------
# HLO pins
# ---------------------------------------------------------------------------

def _lowered_text(cfg, compiled=False):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ids, batch = sampler.sample_round(0)
    lowered = sess.round_fn.lower(
        sess.state, jnp.asarray(ids),
        {k: jnp.asarray(v) for k, v in batch.items()}, jnp.float32(0.2))
    return (lowered.compile() if compiled else lowered).as_text()


def test_overlap_none_lowers_byte_identical_hlo():
    """The default stays golden: overlap='none' (explicit or by default)
    traces the exact pre-overlap program — no layout drift, so the
    registry_parity goldens hold by construction."""
    kw = SPARSE_MODES["local_topk"]
    texts = [_lowered_text(Config(**kw, **BASE)),
             _lowered_text(Config(overlap_collectives="none", **kw, **BASE))]
    assert texts[0] == texts[1]


def test_layerwise_fused_round_carries_overlap_scope():
    """The segmented table psums sit under the overlap_layerwise_psum
    scope (parallel/round.py) so profiles attribute them; the monolithic
    build must NOT carry the scope (marker validity)."""
    text_lw = _lowered_text(_fused_cfg(overlap_collectives="layerwise"),
                            compiled=True)
    assert "overlap_layerwise_psum" in text_lw
    text_none = _lowered_text(_fused_cfg(), compiled=True)
    assert "overlap_layerwise_psum" not in text_none


# ---------------------------------------------------------------------------
# config surface
# ---------------------------------------------------------------------------

def test_config_rejects_unknown_overlap_value():
    with pytest.raises(ValueError, match="overlap_collectives"):
        Config(mode="uncompressed", overlap_collectives="chunky", **BASE)


def test_config_rejects_double_buffer_without_async_engine():
    with pytest.raises(ValueError, match="async_double_buffer"):
        Config(mode="sketch", k=40, num_rows=3, num_cols=256,
               async_double_buffer=True, **BASE)
