"""Data-layer tests: sharding semantics, determinism, batch shapes."""

import numpy as np
import pytest

from commefficient_tpu.data import (
    FedDataset,
    FedSampler,
    load_fed_cifar10,
    load_fed_emnist,
    load_fed_personachat,
    augment_batch,
)


def _toy(n=1000, num_classes=10, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "x": rng.normal(size=(n, 8)).astype(np.float32),
        "y": rng.integers(0, num_classes, size=n).astype(np.int32),
    }


def test_iid_split_partitions_everything():
    ds = FedDataset(_toy(), num_clients=7, iid=True, seed=1)
    allix = np.concatenate(ds.client_indices)
    assert len(allix) == 1000
    assert len(np.unique(allix)) == 1000
    assert ds.images_per_client.min() >= 1000 // 7


def test_non_iid_split_concentrates_labels():
    data = _toy(n=2000)
    iid = FedDataset(data, num_clients=20, iid=True, seed=1)
    non = FedDataset(data, num_clients=20, iid=False, seed=1)
    # labels seen per client: non-IID clients see far fewer distinct labels
    nuniq = lambda ds: np.mean([len(np.unique(data["y"][ix])) for ix in ds.client_indices])
    assert nuniq(non) <= 4 < nuniq(iid)
    allix = np.concatenate(non.client_indices)
    assert len(np.unique(allix)) == 2000  # still a partition


def test_split_deterministic_across_instances():
    a = FedDataset(_toy(), num_clients=5, iid=False, seed=9)
    b = FedDataset(_toy(), num_clients=5, iid=False, seed=9)
    for ia, ib in zip(a.client_indices, b.client_indices):
        np.testing.assert_array_equal(ia, ib)


def test_sampler_round_shapes_and_determinism():
    ds = FedDataset(_toy(), num_clients=16, seed=3)
    s = FedSampler(ds, num_workers=4, local_batch_size=8, seed=3)
    ids1, batch1 = s.sample_round(5)
    ids2, batch2 = s.sample_round(5)
    np.testing.assert_array_equal(ids1, ids2)
    np.testing.assert_array_equal(batch1["x"], batch2["x"])
    assert ids1.shape == (4,)
    assert len(np.unique(ids1)) == 4  # distinct participants
    assert batch1["x"].shape == (4, 8, 8)
    assert batch1["y"].shape == (4, 8)


def test_sampler_batches_come_from_the_right_client():
    data = _toy()
    ds = FedDataset(data, num_clients=10, iid=False, seed=0)
    s = FedSampler(ds, num_workers=3, local_batch_size=4, seed=0)
    ids, batch = s.sample_round(0)
    for w, cid in enumerate(ids):
        client_rows = data["x"][ds.client_indices[cid]]
        for b in range(4):
            assert (batch["x"][w, b] == client_rows).all(axis=1).any()


def test_cifar10_synthetic_fallback_pipeline(tmp_path):
    tr, te, real = load_fed_cifar10(str(tmp_path), num_clients=8, iid=False)
    assert not real
    assert tr.data["x"].shape[1:] == (32, 32, 3)
    # batches stay uint8 end-to-end on the host; normalization happens on
    # device inside the loss (device_normalizer) — 4x less tunnel traffic
    assert tr.data["x"].dtype == np.uint8
    s = FedSampler(tr, num_workers=4, local_batch_size=2, augment=augment_batch, seed=0)
    _, batch = s.sample_round(0)
    assert batch["x"].shape == (4, 2, 32, 32, 3)
    assert batch["x"].dtype == np.uint8


def test_femnist_natural_clients(tmp_path):
    tr, te, real = load_fed_emnist(str(tmp_path), num_clients=12)
    assert not real
    assert tr.num_clients == 12
    assert tr.data["x"].shape[1:] == (28, 28, 1)
    # naturally non-IID: each client sees a small subset of the 62 classes
    for ix in tr.client_indices:
        assert len(np.unique(tr.data["y"][ix])) <= 15


def test_femnist_label_noise_reconstructible(tmp_path):
    """label_noise now reaches the synthetic stand-in through Config/CLI
    (ADVICE r5 on data/emnist.py): --label_noise 0 reconstructs the pre-r5
    (r4) noise-free distribution exactly; the default 0.06 flips ~6% of
    labels WITHIN each client's class subset (inputs untouched)."""
    clean_tr, clean_te, _ = load_fed_emnist(
        str(tmp_path), num_clients=10, label_noise=0.0
    )
    noisy_tr, noisy_te, _ = load_fed_emnist(
        str(tmp_path), num_clients=10, label_noise=0.3
    )
    default_tr, _, _ = load_fed_emnist(str(tmp_path), num_clients=10)
    # inputs are bit-identical across noise settings — only labels move
    np.testing.assert_array_equal(clean_tr.data["x"], noisy_tr.data["x"])
    flipped = np.mean(clean_tr.data["y"] != noisy_tr.data["y"])
    # relabels draw uniformly from the client's OWN subset, so a ~1/|C|
    # fraction of flips lands back on the true class: observed rate is
    # p*(1 - E[1/|C|]) ~ 0.3 * 0.885
    assert 0.18 < flipped < 0.3
    # the noise stays inside each client's class subset (non-IID structure
    # — the thing FEMNIST exists to test — is preserved)
    for ix in noisy_tr.client_indices:
        assert set(np.unique(noisy_tr.data["y"][ix])) <= set(
            np.unique(clean_tr.data["y"][ix])
        )
    # the default (0.06) is noisy: r4 reconstruction REQUIRES passing 0
    assert np.any(default_tr.data["y"] != clean_tr.data["y"])

    # BIT-EXACT r4 reconstruction: label_noise=0 must reproduce the
    # pre-r5 generator's draw sequence (this inline oracle is the r4
    # algorithm verbatim — commit ebb267a's _synthetic_femnist)
    rng = np.random.default_rng(42)  # load_fed_emnist's default seed
    protos = rng.normal(0, 1, size=(62, 28, 28, 1)).astype(np.float32)
    xs, ys = [], []
    for _ in range(10):
        style = rng.normal(0, 0.5, size=(28, 28, 1)).astype(np.float32)
        classes = rng.choice(62, size=rng.integers(5, 15), replace=False)
        y = rng.choice(classes, size=120).astype(np.int32)
        x = protos[y] + style + rng.normal(
            0, 0.3, size=(120, 28, 28, 1)
        ).astype(np.float32)
        xs.append(x.astype(np.float32))
        ys.append(y)
    r4_x, r4_y = np.concatenate(xs), np.concatenate(ys)
    # the train FedDataset holds the FULL generated arrays (client_indices
    # carve the train/test views), so the comparison is direct + bit-exact
    np.testing.assert_array_equal(clean_tr.data["y"], r4_y)
    np.testing.assert_array_equal(clean_tr.data["x"], r4_x)


def test_personachat_assembly_contract(tmp_path):
    tr, te, real, vocab = load_fed_personachat(
        str(tmp_path), num_clients=6, num_candidates=2, max_seq_len=64
    )
    assert not real
    d = tr.data
    N, C, T = d["input_ids"].shape
    assert C == 2 and T == 64
    assert d["lm_labels"].shape == (N, C, T)
    assert d["mc_token_ids"].shape == (N, C)
    # only the true (last) candidate carries LM labels
    assert (d["lm_labels"][:, :-1] == -100).all()
    assert (d["lm_labels"][:, -1] != -100).any(axis=-1).all()
    # mc_token points at a real (non-pad) position
    pad = vocab - 1
    for i in range(min(N, 10)):
        for c in range(C):
            t = d["mc_token_ids"][i, c]
            assert d["input_ids"][i, c, t] != pad
    # all ids within vocab
    assert d["input_ids"].max() < vocab


def test_cifar100_loader_synthetic_fallback(tmp_path):
    from commefficient_tpu.data import load_fed_cifar100

    train, test, real = load_fed_cifar100(str(tmp_path), num_clients=10)
    assert not real
    assert train.data["y"].max() == 99 and train.data["y"].min() == 0
    assert train.data["x"].shape[1:] == (32, 32, 3)
    assert train.num_clients == 10


def test_cifar100_loader_real_pickles(tmp_path):
    """The cifar-100-python pickle layout is read when present."""
    import pickle

    import numpy as np

    from commefficient_tpu.data import load_fed_cifar100

    d = tmp_path / "cifar-100-python"
    d.mkdir()
    rng = np.random.default_rng(0)
    for name, n in (("train", 40), ("test", 20)):
        raw = {
            b"data": rng.integers(0, 255, size=(n, 3072), dtype=np.uint8).astype(np.uint8),
            b"fine_labels": rng.integers(0, 100, size=n).tolist(),
        }
        with open(d / name, "wb") as f:
            pickle.dump(raw, f)
    train, test, real = load_fed_cifar100(str(tmp_path), num_clients=4)
    assert real
    assert len(train) == 40 and len(test) == 20


def test_imagenet_imagefolder_decode_and_cache(tmp_path):
    """ImageFolder JPEG tree decodes via PIL and caches to .npy."""
    import numpy as np
    import pytest

    PIL = pytest.importorskip("PIL")
    from PIL import Image

    from commefficient_tpu.data import load_fed_imagenet

    root = tmp_path / "imagenet" / "train"
    rng = np.random.default_rng(0)
    for wnid in ("n01440764", "n01443537"):
        (root / wnid).mkdir(parents=True)
        for i in range(3):
            arr = rng.integers(0, 255, size=(80, 96, 3), dtype=np.uint8)
            Image.fromarray(arr.astype(np.uint8)).save(root / wnid / f"{i}.JPEG")
    train, test, real = load_fed_imagenet(
        str(tmp_path), num_clients=2, iid=True, synthetic_size=64
    )
    assert real
    assert train.data["x"].shape[1:] == (64, 64, 3)
    assert set(np.unique(np.concatenate([train.data["y"], test.data["y"]]))) == {0, 1}
    # the decode was cached for the next run
    assert (tmp_path / "imagenet" / "imagenet_x.npy").exists()
    train2, _, real2 = load_fed_imagenet(str(tmp_path), num_clients=2, iid=True)
    assert real2 and len(train2) == len(train)
