"""Elastic fleet (README "Elastic fleet") — worker join/leave as
first-class, zero-retrace width re-partitioning.

The load-bearing pins:

  * schedule — fleet events (``resize@W'``/``leave@n``/``join@n``/
    ``shrink@W'``) fold deterministically over the base width, are
    validated against the fixed device mesh at Config construction, and
    engines that cannot re-shape a round mid-run are refused there;
  * zero retrace — every realized width dispatches an AOT-prewarmed
    per-width round program: ``xla/retraces == 0`` across shrink AND
    grow transitions, at session level and through the REAL shared
    runner, and a width-W' round is bit-identical to a fresh session
    provisioned at W';
  * recovery — an UNSCHEDULED loss (``shrink@W'``) surfaces as
    ``FleetShrinkError`` and heals under ``--recover_policy retry`` into
    a run bit-identical to the SCHEDULED ``resize@W'`` twin — params,
    scalars, and the ledger's exact byte accounting;
  * gates — ``availability='always'`` with no fleet events constructs
    NOTHING new (empty width tables), preserving golden parity.

Multi-host satellites (topology width re-split, coordinator connect
retry) are pinned here too; the staleness-aware control loop lives in
tests/test_control.py.
"""

import importlib.util
import json
import os

import numpy as np
import pytest
from test_round import BASE, _setup

from commefficient_tpu.data import FedDataset, FedSampler
from commefficient_tpu.fedsim import parse_chaos
from commefficient_tpu.fedsim.env import FedEnvironment
from commefficient_tpu.fedsim.faults import (
    fleet_shrink_at,
    fleet_transitions,
    fleet_width_at,
    fleet_widths,
    validate_chaos_rounds,
)
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.telemetry.flight import FleetShrinkError
from commefficient_tpu.utils.checkpoint import FedCheckpointer
from commefficient_tpu.utils.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(REPO, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# schedule: grammar + fold + validation
# ---------------------------------------------------------------------------

def test_fleet_events_fold_in_start_order():
    plan = parse_chaos("resize@4:rounds=3-5")
    assert [fleet_width_at(plan, 8, r) for r in range(7)] == [
        8, 8, 8, 4, 4, 4, 8]
    assert fleet_transitions(plan, 8) == ((3, 4), (6, 8))
    assert fleet_widths(plan, 8) == (8, 4)
    # deltas compose relative to the width in effect as each window opens
    plan = parse_chaos("leave@4:rounds=2-,join@2:rounds=6-")
    assert [fleet_width_at(plan, 8, r) for r in (0, 2, 5, 6, 9)] == [
        8, 4, 4, 6, 6]
    assert fleet_transitions(plan, 8) == ((2, 4), (6, 6))
    assert fleet_widths(plan, 8) == (8, 4, 6)
    # shrink surfaces only at the round its window OPENS — replays and
    # later in-window rounds run the width quietly
    plan = parse_chaos("shrink@4:rounds=5-")
    assert fleet_shrink_at(plan, 5) == 4
    assert fleet_shrink_at(plan, 6) is None
    assert fleet_width_at(plan, 8, 7) == 4


def test_open_ended_fleet_window_validated_against_run_length():
    validate_chaos_rounds(parse_chaos("resize@4:rounds=3-"), 9)
    with pytest.raises(ValueError, match="only 9 rounds"):
        validate_chaos_rounds(parse_chaos("resize@4:rounds=12-"), 9)


@pytest.mark.parametrize("bad", [
    "resize@0:rounds=3-", "resize@2.5:rounds=3-", "join@0",
])
def test_fleet_grammar_rejects_non_positive_widths(bad):
    with pytest.raises(ValueError, match="positive integer worker count"):
        parse_chaos(bad)


_FLEET_KW = dict(mode="uncompressed", num_clients=16, num_workers=8,
                 num_devices=4, local_batch_size=4, seed=5)


@pytest.mark.parametrize("kw,match", [
    # realized widths must shard the FIXED mesh and stay provisioned
    (dict(chaos="resize@6:rounds=3-"), r"not a multiple of num_devices"),
    (dict(chaos="join@4:rounds=3-"), r"provisioned maximum"),
    (dict(chaos="leave@8:rounds=3-"), r">= 1"),
    # engines that cannot re-shape a round mid-run
    (dict(chaos="resize@4:rounds=3-", async_buffer=4,
          async_concurrency=2), r"async_buffer"),
    (dict(chaos="resize@4:rounds=3-", scan_rounds=2), r"scan_rounds"),
    (dict(chaos="resize@4:rounds=3-", pipeline_depth=2),
     r"pipeline_depth"),
    (dict(chaos="resize@4:rounds=3-", fsdp=True), r"fsdp"),
    # shrink models a LOSS: needs the recovery path, a round to roll
    # back over, and a width strictly below the one in effect
    (dict(chaos="shrink@4:rounds=5-"), r"recover_policy"),
    (dict(chaos="shrink@4:rounds=0-", recover_policy="retry",
          telemetry_level=1), r"round >= 1"),
    (dict(chaos="shrink@8:rounds=5-", recover_policy="retry",
          telemetry_level=1), r"strictly below"),
])
def test_config_rejects_bad_fleet_plans(kw, match):
    with pytest.raises(ValueError, match=match):
        Config(**{**_FLEET_KW, **kw})


def test_fleet_disabled_constructs_nothing():
    """The construction gate golden parity rides on: no fleet events —
    even with OTHER chaos scheduled — builds zero width programs, and
    the fleet dispatch state stays at the base width."""
    for kw in (dict(), dict(chaos="dropout@0.3:rounds=2-4",
                            telemetry_level=1)):
        cfg = Config(**{**_FLEET_KW, **kw})
        assert not cfg.fleet_enabled
        _ds, params, loss_fn = _setup(cfg.num_clients)
        sess = FederatedSession(cfg, params, loss_fn)
        assert all(not r.width_fns and not r.width_idx_fns
                   for r in sess.rungs)
        assert sess._fleet_width == cfg.num_workers
        assert sess._fleet_resize_ms == 0.0


def test_env_width_schedule_and_stats():
    env = FedEnvironment(Config(**_FLEET_KW, chaos="resize@4:rounds=3-5"))
    assert env.has_fleet
    assert env.widths() == (8, 4)
    assert env.transitions == ((3, 4), (6, 8))
    assert env.shrink_at(3) is None
    for r, (w, n, last) in enumerate([(8, 0, -1), (8, 0, -1), (8, 0, -1),
                                      (4, 1, 3), (4, 1, 3), (4, 1, 3),
                                      (8, 2, 6)]):
        assert env.fleet_stats(r) == {
            "fleet/width": float(w), "fleet/resizes": float(n),
            "fleet/last_resize_round": float(last)}, r
    # and the fleet/* scalars ride round_env's stats dict
    assert env.round_env(3).stats["fleet/width"] == 4.0
    # fleet-less env: empty stats, constant base width
    env0 = FedEnvironment(Config(**_FLEET_KW, chaos="dropout@0.2"))
    assert not env0.has_fleet and env0.fleet_stats(0) == {}
    assert env0.width_at(5) == 8 and env0.widths() == (8,)


# ---------------------------------------------------------------------------
# session: per-width programs, zero-retrace dispatch, parity
# ---------------------------------------------------------------------------

def _session_inputs(cfg, n=None):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    return sess, sampler


def test_resized_round_matches_fresh_session_at_new_width():
    """Width parity: a round dispatched through the width ladder at W'=4
    is bit-identical to one from a session PROVISIONED at num_workers=4
    — the re-partitioned program is the real program, not an
    approximation of it."""
    cfg8 = Config(**{**_FLEET_KW, "chaos": "resize@4:rounds=0-"})
    # dropout@0.0 keeps session B on the fedsim-masked round path (all
    # slots live, like A) without scheduling any fleet event
    cfg4 = Config(**{**_FLEET_KW, "num_workers": 4,
                     "chaos": "dropout@0.0:rounds=0-0"})
    sess8, sampler = _session_inputs(cfg8)
    sess4, _ = _session_inputs(cfg4)
    ids, batch = sampler.sample_round(0)
    m8 = sess8.train_round(ids, batch, 0.3)  # slices to the 4 live rows
    m4 = sess4.train_round(np.asarray(ids)[:4],
                           {k: v[:4] for k, v in batch.items()}, 0.3)
    assert float(m8["loss"]) == float(m4["loss"])
    assert m8["fleet/width"] == 4.0
    np.testing.assert_array_equal(np.asarray(sess8.state.params_vec),
                                  np.asarray(sess4.state.params_vec))


def test_session_resize_zero_retraces_and_scalars():
    """The tentpole claim at session level: 8 -> 4 -> 8 through prewarmed
    width programs with the retrace sentinel pinned at EXACTLY zero, the
    schedule-derived fleet/* scalars riding every round, and the swap
    cost accumulating on the host gauge."""
    cfg = Config(mode="true_topk", error_type="virtual",
                 virtual_momentum=0.9, k=40, topk_method="threshold",
                 telemetry_level=1,
                 **{k: v for k, v in BASE.items() if k != "num_devices"},
                 num_devices=4, chaos="resize@4:rounds=3-5")
    sess, sampler = _session_inputs(cfg)
    assert sess.fedsim_env.widths() == (8, 4)
    assert all(4 in r.width_fns for r in sess.rungs)
    n = sess.prewarm_from_sampler(sampler, 0.3)
    assert n == 2  # (1 rung) x (base + width-4) programs
    widths, losses = [], []
    for r in range(8):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, 0.3)
        losses.append(float(m["loss"]))
        widths.append(m["fleet/width"])
        assert m["xla/retraces"] == 0.0, f"retraced at round {r}"
        assert m["fleet/shrink_recoveries"] == 0.0
    assert widths == [8.0, 8.0, 8.0, 4.0, 4.0, 4.0, 8.0, 8.0]
    assert np.all(np.isfinite(losses))
    assert sess.retrace_sentinel.retraces == 0
    assert m["fleet/resizes"] == 2.0
    assert m["fleet/last_resize_round"] == 6.0
    assert sess._fleet_resize_ms > 0.0  # two dispatch-table swaps


def test_unprewarmed_shrink_raises_fleet_shrink_error():
    """The unscheduled-loss surface: a shrink window opening is an
    exception on the round's FIRST execution (typed with the old and new
    widths for the manager), and a DivergenceError subclass so every
    existing recovery plumbing catches it."""
    from commefficient_tpu.telemetry import DivergenceError

    cfg = Config(mode="true_topk", error_type="virtual",
                 virtual_momentum=0.9, k=40, topk_method="threshold",
                 telemetry_level=1, recover_policy="retry",
                 **{k: v for k, v in BASE.items() if k != "num_devices"},
                 num_devices=4, chaos="shrink@4:rounds=2-")
    sess, sampler = _session_inputs(cfg)
    for r in range(2):
        ids, batch = sampler.sample_round(r)
        sess.train_round(ids, batch, 0.3)
    ids, batch = sampler.sample_round(2)
    with pytest.raises(DivergenceError) as ei:
        sess.train_round(ids, batch, 0.3)
    exc = ei.value
    assert isinstance(exc, FleetShrinkError)
    assert exc.step == 2 and exc.fleet_width == 4 and exc.prev_width == 8
    # the raise marked the round executed: a rollback replay runs the
    # shrunk width QUIETLY (transient-fault semantics, like nan_client)
    m = sess.train_round(ids, batch, 0.3)
    assert m["fleet/width"] == 4.0


# ---------------------------------------------------------------------------
# the shared runner at TinyMLP scale (acceptance twins)
# ---------------------------------------------------------------------------

_RUNNER_BASE = dict(
    mode="true_topk", error_type="virtual", virtual_momentum=0.9, k=40,
    topk_method="threshold", telemetry_level=1, perf_audit=False,
    num_epochs=1, pivot_epoch=1, lr_scale=0.1, num_devices=4,
)


def _run_loop(tmp_path, tag, ckpt_kw=None, **kw):
    """One TinyMLP run through the REAL shared runner (cv_train's
    train_loop adapter). 9 rounds (600 samples / (8 workers x 8 batch));
    availability stays 'always' so the realized fleet width is the only
    participation signal and the ledger arithmetic is exact."""
    from commefficient_tpu.train.cv_train import train_loop
    from commefficient_tpu.utils.logging import MetricsWriter

    base = {**BASE, "local_batch_size": 8, "num_devices": 4}
    cfg = Config(**{**base, **_RUNNER_BASE, **(ckpt_kw or {}), **kw})
    ds, params, loss_fn = _setup(cfg.num_clients)
    test_ds = FedDataset({"x": ds.data["x"][:40], "y": ds.data["y"][:40]},
                         1, seed=0)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    run_dir = str(tmp_path / f"run{tag}")
    writer = MetricsWriter(run_dir, cfg=cfg)
    ck = FedCheckpointer(cfg)
    try:
        val = train_loop(cfg, sess, sampler, test_ds, writer,
                         eval_batch_size=32, checkpointer=ck)
    finally:
        ck.close()
        writer.close()
    return sess, run_dir, val


def _scalars(run_dir, exclude=("resilience/", "trace/",
                               "fleet/shrink_recoveries",
                               "xla/exposed_collective_ms")):
    """(name, value, step) deduped to the LAST occurrence per (name,
    step) — replayed rounds keep the healed values (the determinism
    contract tests/test_resilience.py documents)."""
    rows = {}
    with open(os.path.join(run_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "name" not in rec or rec["name"].startswith(exclude):
                continue
            rows[(rec["name"], rec["step"])] = (
                rec["name"], rec["value"], rec["step"])
    return list(rows.values())


def _series(run_dir, name):
    return [v for n, v, _s in sorted(_scalars(run_dir, exclude=()),
                                     key=lambda t: t[2]) if n == name]


def test_runner_resize_e2e_zero_retraces_schema_v13(tmp_path):
    """Acceptance: the scheduled resize through the REAL runner — the
    width walks 8 -> 4 -> 8 on schedule, every round reports zero
    retraces, and the full artifact set validates under schema v13."""
    sess, run_dir, val = _run_loop(tmp_path, "_resize",
                                   chaos="resize@4:rounds=3-5")
    assert val and np.isfinite(val["loss"])
    assert _series(run_dir, "fleet/width") == [
        8.0, 8.0, 8.0, 4.0, 4.0, 4.0, 8.0, 8.0, 8.0]
    assert _series(run_dir, "fleet/resizes")[-1] == 2.0
    assert set(_series(run_dir, "xla/retraces")) == {0.0}
    assert sess.retrace_sentinel.retraces == 0
    _checker().validate_run_dir(run_dir)
    # the ledger billed each round at its REALIZED width
    ledger = json.loads(open(
        os.path.join(run_dir, "comm_ledger.json")).read())
    assert ledger["live_client_rounds"] == 6 * 8 + 3 * 4


def test_shrink_recovery_retry_matches_scheduled_resize(tmp_path):
    """Acceptance: an UNSCHEDULED shrink healed under retry is
    bit-identical to the SCHEDULED resize twin — final params, deduped
    scalars, and the ledger byte-for-byte (replayed rounds bill once)."""
    sess_a, run_a, _ = _run_loop(tmp_path, "_sched",
                                 chaos="resize@4:rounds=5-")
    sess_b, run_b, _ = _run_loop(tmp_path, "_shrink",
                                 chaos="shrink@4:rounds=5-",
                                 recover_policy="retry", snapshot_every=4)
    np.testing.assert_array_equal(np.asarray(sess_b.state.params_vec),
                                  np.asarray(sess_a.state.params_vec))
    assert sorted(_scalars(run_b)) == sorted(_scalars(run_a))
    assert _series(run_b, "resilience/recoveries")[-1] == 1.0
    assert _series(run_b, "fleet/shrink_recoveries")[-1] == 1.0
    assert sess_b._fleet_shrink_recoveries == 1
    assert sess_b.retrace_sentinel.retraces == 0
    la = json.loads(open(os.path.join(run_a, "comm_ledger.json")).read())
    lb = json.loads(open(os.path.join(run_b, "comm_ledger.json")).read())
    assert lb == la  # the rollback rewound the accounting exactly
    assert lb["live_client_rounds"] == 5 * 8 + 4 * 4
    _checker().validate_run_dir(run_b)
    # the recovery history names the shrunk width
    rec = json.loads(open(
        os.path.join(run_b, "flight_5_recovery.json")).read())
    hist = rec["recovery_history"]
    assert len(hist) == 1 and hist[0]["outcome"] == "recovered"
    assert hist[0]["fleet_width"] == 4


@pytest.mark.slow  # r20 tier budget: secondary composition (preempt x resize);
# restore-at-width is tier-1 via the shrink-recovery rollback twin and the
# runner e2e width series
def test_preempt_resume_lands_inside_resize_window(tmp_path):
    """Checkpoint kill/resume across a resize: a preemption INSIDE the
    shrunk window force-saves, and --resume re-enters at the restored
    round's realized width (4, not the base 8) purely from the round
    clock — the width schedule has no runtime state to lose. The resumed
    run reproduces the uninterrupted twin bit-exactly, still at zero
    retraces."""
    from commefficient_tpu.resilience import PreemptShutdown

    sess_base, _run, _ = _run_loop(tmp_path, "_unint",
                                   chaos="resize@4:rounds=3-5")
    ckpt_dir = str(tmp_path / "ckpt")
    with pytest.raises(PreemptShutdown) as ei:
        _run_loop(tmp_path, "_pre", chaos="resize@4:rounds=3-5,preempt@4",
                  ckpt_kw=dict(checkpoint_dir=ckpt_dir,
                               checkpoint_every=100))
    assert ei.value.step == 5 and ei.value.saved
    sess, run_dir, _ = _run_loop(
        tmp_path, "_res", chaos="resize@4:rounds=3-5,preempt@4",
        resume=True,
        ckpt_kw=dict(checkpoint_dir=ckpt_dir, checkpoint_every=100))
    assert sess._fleet_width == 8  # grew back on schedule after round 5
    assert _series(run_dir, "fleet/width") == [4.0, 8.0, 8.0, 8.0]
    assert sess.retrace_sentinel.retraces == 0
    np.testing.assert_array_equal(np.asarray(sess.state.params_vec),
                                  np.asarray(sess_base.state.params_vec))


# ---------------------------------------------------------------------------
# multi-host satellites: width re-split + coordinator connect retry
# ---------------------------------------------------------------------------

def test_host_topology_at_width():
    from commefficient_tpu.multihost import HostTopology

    topo = HostTopology(num_hosts=2, host_id=1, num_workers=8,
                        num_clients=100, chips_per_host=4,
                        slot_range=(4, 8), client_range=(50, 100))
    narrowed = topo.at_width(4)
    assert narrowed.slot_range == (2, 4)
    assert narrowed.workers_per_host == 2
    # chip + client ownership untouched: the mesh never resizes
    assert narrowed.chips_per_host == 4
    assert narrowed.client_range == (50, 100)
    assert topo.at_width(8) is topo  # base width: no new object
    with pytest.raises(ValueError):
        topo.at_width(5)  # must split host-major over 2 hosts


def test_initialize_multihost_retries_then_succeeds(monkeypatch):
    from commefficient_tpu.multihost import bringup

    calls, naps = [], []
    monkeypatch.setattr(bringup.time, "sleep", naps.append)

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("UNAVAILABLE: connection refused")
        return True

    monkeypatch.setattr(bringup, "initialize_distributed", flaky)
    assert bringup._connect_with_retry(Config()) is True
    assert len(calls) == 3
    assert naps == [1.0, 2.0]  # backoff doubles from 1s


def test_initialize_multihost_exhausted_names_coordinator(monkeypatch):
    from commefficient_tpu.multihost import bringup

    monkeypatch.setattr(bringup.time, "sleep", lambda _s: None)
    monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "10.0.0.7:8476")

    def dead():
        raise RuntimeError("UNAVAILABLE: connection refused")

    monkeypatch.setattr(bringup, "initialize_distributed", dead)
    with pytest.raises(RuntimeError, match="10.0.0.7:8476") as ei:
        bringup._connect_with_retry(
            Config(distributed_connect_retries=2))
    msg = str(ei.value)
    assert "2 attempt(s)" in msg and "connection refused" in msg
    assert isinstance(ei.value.__cause__, RuntimeError)
    # the knob is a TOTAL attempt budget, so < 1 is rejected up front
    with pytest.raises(ValueError, match="distributed_connect_retries"):
        Config(distributed_connect_retries=0)
    # and the retry loop floors duck-typed configs at one dial
    calls = []
    monkeypatch.setattr(bringup, "initialize_distributed",
                        lambda: calls.append(1) or True)

    class _Cfg:
        distributed_connect_retries = 0

    assert bringup._connect_with_retry(_Cfg())
    assert len(calls) == 1


def test_ledger_bills_at_realized_width():
    from commefficient_tpu.telemetry import CommLedger

    bpr = {"upload_floats": 20, "download_floats": 100,
           "upload_bytes": 80, "download_bytes": 400}
    led = CommLedger(bpr, mode="uncompressed", num_workers=8,
                     masked=True)
    led.on_round(0, {"fleet/width": 8.0,
                     "fedsim/participation_rate": 1.0,
                     "fedsim/dropped": 0.0})
    led.on_round(1, {"fleet/width": 4.0,
                     "fedsim/participation_rate": 1.0,
                     "fedsim/dropped": 0.0})
    assert led.live_client_rounds == 12
    assert led.cum_up_bytes == 12 * 80
    # the fedsim rates are RELATIVE to the realized width
    led.on_round(2, {"fleet/width": 4.0,
                     "fedsim/participation_rate": 0.5,
                     "fedsim/dropped": 2.0})
    assert led.live_client_rounds == 14
