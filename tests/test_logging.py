"""utils/logging tests: the deferred metrics path (pack/drain) and the
writer/table satellites from the telemetry PR (previously untested)."""

import json

import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.utils.config import Config
from commefficient_tpu.utils.logging import (
    _PACKER_CACHE,
    MetricsWriter,
    TableLogger,
    drain_round_metrics,
    pack_metric_dicts,
)


class RecordingWriter:
    """Minimal MetricsWriter stand-in recording (name, value, step) order."""

    def __init__(self):
        self.events = []
        self.flushes = 0

    def scalar(self, name, value, step):
        self.events.append((name, float(value), int(step)))

    def flush(self):
        self.flushes += 1


# ---------------------------------------------------------------------------
# pack_metric_dicts
# ---------------------------------------------------------------------------

def test_pack_returns_named_matrix():
    dicts = [{"loss": jnp.float32(j), "acc": jnp.float32(10 + j)}
             for j in range(3)]
    names, mat = pack_metric_dicts(dicts)
    assert names == ("acc", "loss")
    np.testing.assert_allclose(mat[:, names.index("loss")], [0, 1, 2])
    np.testing.assert_allclose(mat[:, names.index("acc")], [10, 11, 12])


def test_pack_cache_reused_across_same_shaped_epochs():
    """Same (N, key set) must hit the jit cache — one compile per shape per
    process, not per epoch (the whole point of the packed drain)."""
    dicts = [{"loss": jnp.float32(j), "x": jnp.float32(j)} for j in range(4)]
    pack_metric_dicts(dicts)
    key = (4, ("loss", "x"))
    assert key in _PACKER_CACHE
    cached = _PACKER_CACHE[key]
    pack_metric_dicts([{"loss": jnp.float32(9), "x": jnp.float32(9)}
                       for _ in range(4)])  # second "epoch", same shape
    assert _PACKER_CACHE[key] is cached


def test_pack_rejects_mixed_key_sets():
    dicts = [{"loss": jnp.float32(0)}, {"loss": jnp.float32(1),
                                        "extra": jnp.float32(2)}]
    with pytest.raises(ValueError, match="mixed"):
        pack_metric_dicts(dicts)


# ---------------------------------------------------------------------------
# drain_round_metrics
# ---------------------------------------------------------------------------

def _pending(n, start=0):
    return [(start + j, 0.1 * (j + 1),
             {"loss": jnp.float32(j), "diag/grad_norm": jnp.float32(2 * j)})
            for j in range(n)]


def test_drain_writes_in_step_order_and_clears():
    w = RecordingWriter()
    acc = []
    pending = _pending(4)
    drain_round_metrics(pending, w, lambda loss, m: acc.append(loss))
    assert pending == []
    assert acc == [0.0, 1.0, 2.0, 3.0]
    loss_steps = [s for n, _, s in w.events if n == "train/loss"]
    assert loss_steps == [0, 1, 2, 3]
    # namespaced metric keys are written as scalars verbatim
    diag = [(v, s) for n, v, s in w.events if n == "diag/grad_norm"]
    assert diag == [(0.0, 0), (2.0, 1), (4.0, 2), (6.0, 3)]
    assert w.flushes == 1


def test_drain_before_checkpoint_ordering(tmp_path):
    """The train loops drain BEFORE a checkpoint write (will_save -> drain
    -> maybe_save): every buffered round up to the save step must be on the
    writer before the save happens — a resume fast-forwards past those
    rounds, so anything unflushed at save time is lost for good. Replays
    the loop's exact call sequence against the real FedCheckpointer
    predicate."""
    from commefficient_tpu.utils.checkpoint import FedCheckpointer

    cfg = Config(checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=3)
    ckpt = FedCheckpointer(cfg)
    try:
        w = RecordingWriter()
        events = []  # interleaved ("scalar", step) / ("save", step)
        orig = w.scalar

        def scalar(name, value, step):
            if name == "train/loss":
                events.append(("scalar", step))
            orig(name, value, step)

        w.scalar = scalar
        pending = []
        step = 0
        for r in range(7):
            pending.append((step, 0.1, {"loss": jnp.float32(r)}))
            step += 1
            if ckpt.will_save(step):
                drain_round_metrics(pending, w, lambda *a: None)
                events.append(("save", step))
        drain_round_metrics(pending, w, lambda *a: None)
        saves = [s for kind, s in events if kind == "save"]
        assert saves == [3, 6], "checkpoint predicate drifted"
        for save_step in saves:
            before = [s for kind, s in events[:events.index(("save", save_step))]
                      if kind == "scalar"]
            assert before == list(range(save_step)), (
                f"rounds < {save_step} must be drained before the save"
            )
    finally:
        ckpt.close()


def test_drain_empty_is_noop():
    w = RecordingWriter()
    drain_round_metrics([], w, lambda *a: None)
    assert w.events == [] and w.flushes == 0


# ---------------------------------------------------------------------------
# TableLogger (satellite: late keys must warn once + render, not vanish)
# ---------------------------------------------------------------------------

def test_table_logger_renders_late_keys(capsys):
    t = TableLogger(width=8)
    t.append({"epoch": 1, "loss": 1.5})
    t.append({"epoch": 2, "loss": 1.2, "val_acc": 0.5})
    t.append({"epoch": 3, "loss": 1.0, "val_acc": 0.75})
    out = capsys.readouterr().out
    lines = out.strip().splitlines()
    warnings = [ln for ln in lines if "new column" in ln]
    assert len(warnings) == 1 and "'val_acc'" in warnings[0]
    # the late column's VALUES are rendered from its first appearance on
    assert "0.5000" in out and "0.7500" in out
    # rows stay aligned: every data row renders all known keys
    assert lines[-1].count("|") == 2


def test_table_logger_warns_once_per_key(capsys):
    t = TableLogger()
    t.append({"a": 1})
    t.append({"a": 2, "b": 3})
    t.append({"a": 4, "b": 5})
    t.append({"a": 6, "b": 7, "c": 8})
    out = capsys.readouterr().out
    assert out.count("new column") == 2  # once for 'b', once for 'c'


# ---------------------------------------------------------------------------
# MetricsWriter (satellite: run header + wall-time field)
# ---------------------------------------------------------------------------

def test_metrics_writer_header_and_walltime(tmp_path):
    cfg = Config(mode="sketch", error_type="virtual", k=7, num_rows=3,
                 num_cols=64, virtual_momentum=0.9)
    w = MetricsWriter(str(tmp_path), cfg=cfg)
    w.scalar("train/loss", 1.25, 0)
    w.close()
    with open(tmp_path / "metrics.jsonl") as f:
        recs = [json.loads(line) for line in f]
    header, scalar = recs
    assert header["type"] == "header"
    from commefficient_tpu.telemetry import SCHEMA_VERSION

    assert header["schema_version"] == SCHEMA_VERSION
    assert header["config"]["mode"] == "sketch" and header["config"]["k"] == 7
    assert isinstance(header["jax_version"], str)
    assert "device_kind" in header and "start_time" in header
    assert scalar == {"name": "train/loss", "value": 1.25, "step": 0,
                      "t": pytest.approx(scalar["t"])}
    assert scalar["t"] >= header["time"] > 0


def test_metrics_writer_resume_appends_second_header(tmp_path):
    for _ in range(2):  # two processes appending to one run dir
        w = MetricsWriter(str(tmp_path))
        w.scalar("train/loss", 1.0, 0)
        w.close()
    with open(tmp_path / "metrics.jsonl") as f:
        recs = [json.loads(line) for line in f]
    assert [r.get("type") for r in recs] == ["header", None, "header", None]
