"""The invariant linter (commefficient_tpu/analysis/), enforced in tier-1.

Three invariant families:

  1. the REAL package lints clean under all five rules (the gate — a new
     subsystem that violates traced-purity/rng-stream/collective-axis/
     registry-dispatch/exception-hygiene fails the suite);
  2. every rule actually FIRES on a violating fixture (the
     detects-violation discipline scripts/check_mode_dispatch.py
     established: a lint that cannot fail is a vacuous pass), including
     the call-graph fixture proving traced-purity follows helper-function
     indirection and builder closures;
  3. the pragma grammar round-trips: a reasoned pragma suppresses
     exactly its rule on exactly its lines, a reason-less or
     unknown-rule pragma is itself a violation, and the CLI keeps the
     gate-script JSON-summary contract on every exit path.

Fixtures are written to tmp_path as miniature packages and analyzed with
``run_analyzers(root=...)`` — pure AST, nothing is imported or executed.
"""

import json

from commefficient_tpu.analysis import run_analyzers
from commefficient_tpu.analysis.__main__ import main as cli_main


def _lint_dir(tmp_path, files, rules=None):
    """Write {relpath: source} under tmp_path/fixpkg and lint it."""
    root = tmp_path / "fixpkg"
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
    findings, _ = run_analyzers(root=root, rules=rules)
    return findings


def _by_rule(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# the gate: the real package is clean
# ---------------------------------------------------------------------------


def test_package_lints_clean():
    findings, _ = run_analyzers()
    assert not findings, (
        "the package must lint clean (fix the violation or pragma it "
        "with a reason):\n"
        + "\n".join(f.format(prefix="commefficient_tpu/") for f in findings)
    )


def test_list_rules_matches_analyzers():
    from commefficient_tpu.analysis import analyzer_registry

    reg = analyzer_registry()
    assert set(reg) == {
        "traced-purity", "rng-stream", "collective-axis",
        "registry-dispatch", "exception-hygiene",
    }
    for mod in reg.values():
        assert mod.DESCRIPTION  # --list-rules renders these


# ---------------------------------------------------------------------------
# traced-purity
# ---------------------------------------------------------------------------


def test_purity_detects_direct_violations(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import time\n"
        "import numpy as np\n"
        "import jax\n"
        "\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    t = time.time()\n"
        "    print(x)\n"
        "    n = np.random.default_rng().normal()\n"
        "    y = float(x)\n"
        "    z = x.item()\n"
        "    return t + n + y + z\n"
    )}, rules=["traced-purity"])
    lines = sorted(f.lineno for f in _by_rule(findings, "traced-purity"))
    assert lines == [7, 8, 9, 10, 11], findings


def test_purity_follows_helper_indirection(tmp_path):
    """The call-graph fixture: the banned call sits TWO hops from the
    root, reached through a plain helper call; an identical unreferenced
    twin must NOT be flagged (reachability, not grep)."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import time\n"
        "import jax\n"
        "\n"
        "def deep():\n"
        "    return time.perf_counter()\n"
        "\n"
        "def helper(x):\n"
        "    return x + deep()\n"
        "\n"
        "def lonely(x):\n"
        "    return x + time.perf_counter()\n"
        "\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return helper(x)\n"
    )}, rules=["traced-purity"])
    hits = _by_rule(findings, "traced-purity")
    assert [f.lineno for f in hits] == [5], (
        "expected exactly the reachable deep() hit (line 5), not the "
        f"unreachable lonely() twin: {hits}"
    )


def test_purity_follows_builder_closure_and_shard_map(tmp_path):
    """The round.py shape: shard_map's body closes over a function the
    builder obtained from a maker (`grad_one = make_grad_one(...)`) —
    the alias hop plus the reference edge must connect it."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import time\n"
        "from jax.experimental.shard_map import shard_map\n"
        "\n"
        "def make_grad():\n"
        "    def grad_one(x):\n"
        "        return x + time.time()\n"
        "    return grad_one\n"
        "\n"
        "def build(mesh):\n"
        "    grad_one = make_grad()\n"
        "    def body(x):\n"
        "        return grad_one(x)\n"
        "    return shard_map(body, mesh=mesh, in_specs=None,\n"
        "                     out_specs=None)\n"
    )}, rules=["traced-purity"])
    assert [f.lineno for f in _by_rule(findings, "traced-purity")] == [6]


def test_purity_unwraps_wrapper_and_builder_roots(tmp_path):
    """``jit(sentinel.wrap(f, tag))`` traces f just as surely as
    ``jit(f)`` (the parallel/api.py round_idx_fn shape), and
    ``jit(make_step(cfg))`` traces whatever nested def the builder
    returns — both must contribute call-graph roots."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import time\n"
        "import jax\n"
        "\n"
        "def wrapped(x):\n"
        "    return x + time.time()\n"
        "\n"
        "def make_step(cfg):\n"
        "    def step(x):\n"
        "        return x + time.perf_counter()\n"
        "    return step\n"
        "\n"
        "def build(sentinel, cfg):\n"
        "    a = jax.jit(sentinel.wrap(wrapped, 'tag'))\n"
        "    b = jax.jit(make_step(cfg))\n"
        "    return a, b\n"
    )}, rules=["traced-purity"])
    lines = sorted(f.lineno for f in _by_rule(findings, "traced-purity"))
    assert lines == [5, 9], findings


def test_purity_pallas_root_and_method_resolution(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "from jax.experimental import pallas as pl\n"
        "\n"
        "class Enc:\n"
        "    def device_encode(self, x):\n"
        "        print('impure')\n"
        "        return x\n"
        "\n"
        "def kernel(ref, o_ref, enc):\n"
        "    o_ref[...] = enc.device_encode(ref[...])\n"
        "\n"
        "def run(x, enc):\n"
        "    return pl.pallas_call(kernel, out_shape=None)(x)\n"
    )}, rules=["traced-purity"])
    assert [f.lineno for f in _by_rule(findings, "traced-purity")] == [5]


def test_purity_resolves_defs_under_control_flow(tmp_path):
    """Version-gated definitions (the utils/jax_compat.py shape: ``if
    HAS_VMA: def f ... else: def f ...``) register in the enclosing
    scope, so the call graph follows them."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import time\n"
        "import jax\n"
        "\n"
        "if hasattr(jax, 'new_api'):\n"
        "    def helper(x):\n"
        "        return x + time.time()\n"
        "else:\n"
        "    def helper(x):\n"
        "        return x + time.perf_counter()\n"
        "\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    return helper(x)\n"
    )}, rules=["traced-purity"])
    lines = sorted(f.lineno for f in _by_rule(findings, "traced-purity"))
    # whichever branch defined `helper` last wins the name — but BOTH
    # defs are graph nodes, and at least the bound one must be reached
    assert lines and set(lines) <= {6, 9}, findings


def test_purity_follows_relative_imports_from_init(tmp_path):
    """``from . import helpers`` in an __init__.py anchors at the
    package itself (not one level up), so call-graph edges through
    relative imports resolve."""
    findings = _lint_dir(tmp_path, {
        "__init__.py": (
            "import jax\n"
            "from . import helpers\n"
            "\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return helpers.impure(x)\n"
        ),
        "helpers.py": (
            "import time\n"
            "\n"
            "def impure(x):\n"
            "    return x + time.time()\n"
        ),
    }, rules=["traced-purity"])
    assert [(f.path, f.lineno) for f in
            _by_rule(findings, "traced-purity")] == [("helpers.py", 4)]


def test_purity_ignores_host_code_and_static_coercions(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import time\n"
        "import jax\n"
        "\n"
        "def host_loop():  # never traced: free to use the wall clock\n"
        "    return time.time()\n"
        "\n"
        "@jax.jit\n"
        "def step(x):\n"
        "    k = int(3)  # literal coercion: static, legal\n"
        "    return x * k\n"
    )}, rules=["traced-purity"])
    assert not findings, findings


# ---------------------------------------------------------------------------
# rng-stream
# ---------------------------------------------------------------------------


def test_rng_stream_detects_violations(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import numpy as np\n"
        "import jax\n"
        "\n"
        "def bad(seed):\n"
        "    a = np.random.default_rng()\n"
        "    b = np.random.default_rng(42)\n"
        "    c = np.random.default_rng((seed, 0x123))\n"
        "    d = jax.random.key(7)\n"
        "    e = jax.random.fold_in(d, 0x99)\n"
        "    f = np.random.normal(0, 1)\n"
        "    return a, b, c, e, f\n"
    )}, rules=["rng-stream"])
    lines = sorted(f.lineno for f in _by_rule(findings, "rng-stream"))
    assert lines == [5, 6, 7, 8, 9, 10], findings


def test_rng_stream_accepts_declared_streams(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import numpy as np\n"
        "import jax\n"
        "\n"
        "MY_STREAM = 0xFED51\n"
        "\n"
        "def good(seed, cfg, round_idx):\n"
        "    a = np.random.default_rng((seed, MY_STREAM, round_idx))\n"
        "    b = np.random.default_rng(seed)\n"
        "    c = jax.random.key(cfg.seed)\n"
        "    d = jax.random.fold_in(c, MY_STREAM)\n"
        "    return a, b, d\n"
    )}, rules=["rng-stream"])
    assert not findings, findings


def test_rng_stream_reuse_after_single_binding_and_in_lambda(tmp_path):
    """The textbook silent-correlation bug: bind a key once, consume it
    twice — the one initial assignment must not disable the check (only
    a rebinding BETWEEN the draws legalizes them). Lambda bodies are
    scopes too."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import jax\n"
        "\n"
        "def textbook(seed):\n"
        "    key = jax.random.key(seed)\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    b = jax.random.uniform(key, (2,))\n"
        "    return a + b\n"
        "\n"
        "def in_lambda(key):\n"
        "    return lambda: (jax.random.normal(key, (2,))\n"
        "                    + jax.random.uniform(key, (2,)))\n"
    )}, rules=["rng-stream"])
    lines = sorted(f.lineno for f in _by_rule(findings, "rng-stream"))
    assert lines == [6, 11], findings


def test_rng_stream_literal_tag_inside_seedsequence(tmp_path):
    """A literal stream tag must not hide one call deeper — the
    SeedSequence idiom gets the same tuple-literal scan; derived-only
    entropy (the countsketch shape) stays legal."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import numpy as np\n"
        "\n"
        "def bad(seed):\n"
        "    return np.random.default_rng(\n"
        "        np.random.SeedSequence([seed, 0x123])\n"
        "    )\n"
        "\n"
        "def good(seed, row, purpose):\n"
        "    return np.random.default_rng(\n"
        "        np.random.SeedSequence([seed & 0x7FFF, row, purpose])\n"
        "    )\n"
    )}, rules=["rng-stream"])
    assert [f.lineno for f in _by_rule(findings, "rng-stream")] == [5], \
        findings


def test_rng_stream_branch_exclusive_draws_are_legal(tmp_path):
    """One draw per execution path is not reuse: if/else arms (statement
    and ternary) are mutually exclusive; a draw in the SAME arm as an
    earlier one still counts."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import jax\n"
        "\n"
        "def branched(key, flag):\n"
        "    if flag:\n"
        "        return jax.random.normal(key, (2,))\n"
        "    else:\n"
        "        return jax.random.uniform(key, (2,))\n"
        "\n"
        "def ternary(key, flag):\n"
        "    return (jax.random.normal(key, (2,)) if flag\n"
        "            else jax.random.uniform(key, (2,)))\n"
        "\n"
        "def same_arm(key, flag):\n"
        "    if flag:\n"
        "        a = jax.random.normal(key, (2,))\n"
        "        return a + jax.random.uniform(key, (2,))\n"
        "    return key\n"
    )}, rules=["rng-stream"])
    hits = _by_rule(findings, "rng-stream")
    assert [f.lineno for f in hits] == [16], hits


def test_rng_stream_detects_key_reuse_not_split(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import jax\n"
        "\n"
        "def reuse(key):\n"
        "    a = jax.random.normal(key, (2,))\n"
        "    b = jax.random.normal(key, (2,))\n"
        "    return a + b\n"
        "\n"
        "def split_ok(rng):\n"
        "    rng, r = jax.random.split(rng)\n"
        "    a = jax.random.normal(r, (2,))\n"
        "    rng, r2 = jax.random.split(rng)\n"
        "    return a + jax.random.normal(r2, (2,))\n"
    )}, rules=["rng-stream"])
    hits = _by_rule(findings, "rng-stream")
    assert [f.lineno for f in hits] == [5], hits
    assert "reuse" in hits[0].message or "split" in hits[0].message


# ---------------------------------------------------------------------------
# collective-axis
# ---------------------------------------------------------------------------


def test_collective_axis_detects_literals(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import jax\n"
        "from functools import partial\n"
        "\n"
        "def attn(x):\n"
        "    return x\n"
        "\n"
        "def bad(x):\n"
        "    a = jax.lax.psum(x, 'workers')\n"
        "    b = jax.lax.all_gather(x, axis_name='workers')\n"
        "    c = jax.lax.psum(x, ('model', 'seq'))\n"
        "    d = partial(attn, axis_name='seq')\n"
        "    e = jax.lax.axis_index('workers')\n"
        "    return a, b, c, d, e\n"
    )}, rules=["collective-axis"])
    lines = sorted(f.lineno for f in _by_rule(findings, "collective-axis"))
    # line 10 carries TWO literals in the tuple
    assert lines == [8, 9, 10, 10, 11, 12], findings


def test_collective_axis_accepts_constants(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import jax\n"
        "\n"
        "WORKERS = 'workers'\n"
        "SEQ = 'seq'\n"
        "\n"
        "def good(x, axis_name):\n"
        "    a = jax.lax.psum(x, WORKERS)\n"
        "    b = jax.lax.psum(x, (WORKERS, SEQ))\n"
        "    c = jax.lax.all_gather(x, axis_name)\n"
        "    d = jax.lax.axis_index(axis_name=WORKERS)\n"
        "    return a, b, c, d\n"
    )}, rules=["collective-axis"])
    assert not findings, findings


def test_collective_axis_detects_hardcoded_perm_table(tmp_path):
    """ISSUE 14 satellite: integer literals in a ppermute perm table are
    baked device ids — valid for exactly one mesh size. Tables COMPUTED
    from the axis size (the recursive-halving butterfly, the ring shift —
    whose arithmetic constants live inside BinOps, not id slots) stay
    legal."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import jax\n"
        "\n"
        "WORKERS = 'workers'\n"
        "\n"
        "def bad(x):\n"
        "    return jax.lax.ppermute(\n"
        "        x, WORKERS, perm=[(0, 1), (1, 0)])\n"
        "\n"
        "def bad_positional(x):\n"
        "    return jax.lax.ppermute(x, WORKERS, [(3, 0)])\n"
        "\n"
        "def good(x, axis_size, bit):\n"
        "    butterfly = [(i, i ^ bit) for i in range(axis_size)]\n"
        "    a = jax.lax.ppermute(x, WORKERS, perm=butterfly)\n"
        "    ring = [(i, (i - 1) % axis_size) for i in range(axis_size)]\n"
        "    return jax.lax.ppermute(a, WORKERS, perm=ring)\n"
    )}, rules=["collective-axis"])
    hits = _by_rule(findings, "collective-axis")
    assert sorted(f.lineno for f in hits) == [7, 7, 7, 7, 10, 10], findings
    assert all("perm table" in f.message for f in hits), findings


# ---------------------------------------------------------------------------
# registry-dispatch (ported analyzer; the script shim is covered by
# tests/test_mode_dispatch.py)
# ---------------------------------------------------------------------------


def test_registry_dispatch_on_framework(tmp_path):
    findings = _lint_dir(tmp_path, {
        "train/loop.py": (
            "def f(cfg):\n"
            "    if cfg.mode == 'sketch':\n"
            "        pass\n"
            "    h = {'fixed': 1}[cfg.control_policy]\n"
        ),
        # the home package may dispatch on its own family
        "compress/registry.py": (
            "def g(cfg):\n"
            "    if cfg.mode == 'sketch':\n"
            "        pass\n"
        ),
    }, rules=["registry-dispatch"])
    hits = _by_rule(findings, "registry-dispatch")
    assert [(f.path, f.lineno) for f in hits] == [
        ("train/loop.py", 2), ("train/loop.py", 4),
    ], hits


# ---------------------------------------------------------------------------
# exception-hygiene
# ---------------------------------------------------------------------------


def test_exception_hygiene_detects_and_allows(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except:\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"
        "    try:\n"
        "        g()\n"
        "    except (ImportError, AttributeError):\n"
        "        pass  # narrow swallow: author named what can happen\n"
        "    try:\n"
        "        g()\n"
        "    except Exception as e:\n"
        "        raise RuntimeError('ctx') from e\n"
    )}, rules=["exception-hygiene"])
    lines = sorted(f.lineno for f in _by_rule(findings, "exception-hygiene"))
    assert lines == [4, 8], findings


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------


def test_pragma_suppresses_with_reason(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    # lint: allow[exception-hygiene] probe is best-effort\n"
        "    except Exception:\n"
        "        pass\n"
    )})
    assert not findings, findings


def test_pragma_without_reason_is_a_violation(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    # lint: allow[exception-hygiene]\n"
        "    except Exception:\n"
        "        pass\n"
    )})
    rules = sorted(f.rule for f in findings)
    # the reason-less pragma is flagged AND does not suppress
    assert rules == ["exception-hygiene", "pragma"], findings


def test_pragma_unknown_rule_is_a_violation(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": (
        "X = 1  # lint: allow[not-a-rule] because reasons\n"
    )})
    assert [f.rule for f in findings] == ["pragma"], findings
    assert "not-a-rule" in findings[0].message


def test_pragma_scopes_to_rule_and_line(tmp_path):
    """A pragma for one rule must not silence another rule on the same
    line, nor the same rule elsewhere in the file."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import jax\n"
        "\n"
        "def f(x, key):\n"
        "    # lint: allow[collective-axis] wrong rule on purpose\n"
        "    a = jax.random.key(7)\n"
        "    b = jax.lax.psum(x, 'workers')\n"
        "    return a, b\n"
    )})
    rules = sorted(f.rule for f in findings)
    assert rules == ["collective-axis", "rng-stream"], findings


def test_trailing_pragma_does_not_leak_to_next_line(tmp_path):
    """A trailing pragma covers only its own line/statement: a
    violation inserted on the NEXT line must not silently inherit the
    exemption (only standalone comment-line pragmas cover downward)."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import jax\n"
        "\n"
        "def f(x):\n"
        "    a = jax.lax.psum(x, 'w')  "
        "# lint: allow[collective-axis] legacy axis\n"
        "    b = jax.lax.psum(x, 'w')\n"
        "    return a + b\n"
    )}, rules=["collective-axis"])
    assert [f.lineno for f in findings] == [5], findings


def test_pragma_covers_multiline_statement(tmp_path):
    """One pragma atop a multi-line call covers findings on its inner
    lines (the countsketch SeedSequence shape)."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        "import numpy as np\n"
        "\n"
        "def f(seed, row):\n"
        "    # lint: allow[rng-stream] deterministic spec-derived tag\n"
        "    rng = np.random.default_rng(\n"
        "        (seed,\n"
        "         0x123)\n"
        "    )\n"
        "    return rng\n"
    )})
    assert not findings, findings


def test_pragma_in_docstring_is_inert(tmp_path):
    """Quoting the grammar in a docstring/string (as the framework's own
    docs do) must neither suppress nor trip pragma hygiene."""
    findings = _lint_dir(tmp_path, {"mod.py": (
        '"""Docs: use # lint: allow[no-such-rule] here."""\n'
        "MSG = 'also inert: # lint: allow[zzz]'\n"
    )})
    assert not findings, findings


def test_parse_error_is_a_finding(tmp_path):
    findings = _lint_dir(tmp_path, {"mod.py": "def broken(:\n"})
    assert [f.rule for f in findings] == ["parse"], findings


def test_non_utf8_file_is_a_finding_not_a_crash(tmp_path):
    root = tmp_path / "fixpkg"
    root.mkdir()
    (root / "legacy.py").write_bytes(
        b"# -*- coding: latin-1 -*-\n# caf\xe9\nX = 1\n"
    )
    findings, _ = run_analyzers(root=root)
    assert [f.rule for f in findings] == ["parse"], findings
    assert "unreadable" in findings[0].message


# ---------------------------------------------------------------------------
# CLI: exit codes + the JSON summary contract on every exit path
# ---------------------------------------------------------------------------


def _last_json(capsys):
    out = capsys.readouterr().out.strip().splitlines()
    return json.loads(out[-1])


def test_cli_clean_package(capsys):
    assert cli_main([]) == 0
    s = _last_json(capsys)
    assert s["kind"] == "invariant_lint" and s["clean"] is True
    assert s["findings"] == [] and len(s["rules"]) == 5


def test_cli_violations_exit_1(tmp_path, capsys):
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "bad.py").write_text(
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'workers')\n"
    )
    assert cli_main(["--root", str(root)]) == 1
    s = _last_json(capsys)
    assert s["clean"] is False
    assert s["counts"] == {"collective-axis": 1}
    assert s["findings"][0]["path"] == "pkg/bad.py"
    assert s["findings"][0]["line"] == 3


def test_cli_rules_subset_and_json_flag(tmp_path, capsys):
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "bad.py").write_text(
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'workers')\n"
    )
    # a subset NOT containing the violated rule passes...
    assert cli_main(["--root", str(root), "--rules", "rng-stream"]) == 0
    s = _last_json(capsys)
    assert s["rules"] == ["rng-stream"] and s["clean"] is True
    # ...and --json emits exactly one line (the summary)
    assert cli_main(["--root", str(root), "--json"]) == 1
    out = capsys.readouterr().out.strip().splitlines()
    assert len(out) == 1
    assert json.loads(out[0])["clean"] is False


def test_cli_duplicate_rules_run_once(tmp_path, capsys):
    root = tmp_path / "pkg"
    root.mkdir()
    (root / "bad.py").write_text(
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'workers')\n"
    )
    assert cli_main(["--root", str(root),
                     "--rules", "collective-axis,collective-axis"]) == 1
    s = _last_json(capsys)
    assert s["counts"] == {"collective-axis": 1}  # not doubled
    assert s["rules"] == ["collective-axis"]


def test_cli_usage_errors_keep_summary_contract(capsys):
    assert cli_main(["--rules", "bogus"]) == 2
    s = _last_json(capsys)
    assert s["kind"] == "invariant_lint" and "bogus" in s["error"]
    assert cli_main(["--root", "/nonexistent-dir-xyz"]) == 2
    s = _last_json(capsys)
    assert "error" in s
    # an empty selection (e.g. --rules "$UNSET_VAR") must be a usage
    # error, not a zero-analyzer vacuous pass
    assert cli_main(["--rules", ""]) == 2
    s = _last_json(capsys)
    assert "no rules" in s["error"]


def test_cli_root_dot_keeps_real_prefix(tmp_path, capsys, monkeypatch):
    """--root . resolves to the directory's real name, not a bare '/'
    prefix that reads as an absolute path."""
    root = tmp_path / "pkgdot"
    root.mkdir()
    (root / "bad.py").write_text(
        "import jax\n"
        "def f(x):\n"
        "    return jax.lax.psum(x, 'workers')\n"
    )
    monkeypatch.chdir(root)
    assert cli_main(["--root", ".", "--json"]) == 1
    s = _last_json(capsys)
    assert s["findings"][0]["path"] == "pkgdot/bad.py"


def test_cli_list_rules(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule in ("traced-purity", "rng-stream", "collective-axis",
                 "registry-dispatch", "exception-hygiene"):
        assert rule in out
    s = json.loads(out.strip().splitlines()[-1])
    assert s["listed"] is True and s["clean"] is True


def test_scripts_lint_shim_matches_module(tmp_path):
    """scripts/lint.py is the same entry point by path."""
    import subprocess
    import sys
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(repo, "scripts", "lint.py"),
         "--json"],
        capture_output=True, text=True, cwd=repo, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    s = json.loads(r.stdout.strip().splitlines()[-1])
    assert s["kind"] == "invariant_lint" and s["clean"] is True


# ---------------------------------------------------------------------------
# the self-application is real: the package carries reasoned pragmas
# ---------------------------------------------------------------------------


def test_package_pragmas_all_carry_reasons():
    """Every pragma in the real package names a known rule and a reason
    (the clean gate implies this, but assert it directly so a pragma
    regression fails with a pointed message), and the known intentional
    exemptions are present — the trace-time sketch constants and the
    best-effort telemetry swallows."""
    from commefficient_tpu.analysis import PackageIndex, analyzer_registry
    from commefficient_tpu.analysis.core import PACKAGE_ROOT

    index = PackageIndex(PACKAGE_ROOT)
    known = set(analyzer_registry())
    all_pragmas = [(f.rel, p) for f in index.files.values()
                   for p in f.pragmas]
    assert all_pragmas, "expected the package to carry lint pragmas"
    for rel, p in all_pragmas:
        assert p.rule in known, f"{rel}:{p.lineno}: unknown rule {p.rule}"
        assert p.reason, f"{rel}:{p.lineno}: pragma without a reason"
    by_file = {rel for rel, _ in all_pragmas}
    assert "ops/countsketch.py" in by_file  # seed-derived trace constants
    assert "telemetry/ledger.py" in by_file  # best-effort metadata
