"""Round tracing & critical-path attribution (telemetry/trace.py, PR 18).

What this file pins, and why it is shaped as three runs instead of the
one the acceptance sentence names: ``async_buffer`` is mutually
exclusive with BOTH ``pipeline_depth`` and hosted client stores
(utils/config.py _validate_asyncfed — the asyncfed engine owns its own
cohort prefetch window and requires HBM-resident banks), so "pipelined +
async + hosted-clientstore" is covered by a pipelined+hosted run (depth
2, ``--client_store host``) and an async run (C = 3) whose span dumps
together carry every prefetch/writeback/apply span with the owning
round's/cohort's trace id.

  * trace-id grammar: deterministic ids minted at realization time —
    ``r<step>`` for rounds, ``c<cohort>`` for async cohorts (parent =
    the launching round's id); ``step_of_trace_id`` inverts only round
    ids.
  * CriticalPath: the exclusive decomposition is DISJOINT — stage times
    sum to exactly the round wall-clock (idle is the remainder), exposed
    collective is assigned first, and non-path spans
    (async_buffer_residency) never stretch the round window.
  * e2e: the pipelined+hosted dump validates under schema v11, every
    prefetch/gather/writeback span carries its round's id, the lagged
    ``trace/*`` scalars ride the metric stream with a constant key set,
    and the run dir round-trips through write_run_report ->
    validate_run_report -> scripts/analyze_run.py.
  * level-0 discipline: tracing is host-side only — the lowered HLO at
    ``--telemetry_level 0`` is byte-identical with spans attached and a
    ``--profile_rounds`` window configured, and a rung switch under a
    hosted store with tracing active still retraces nothing.
"""

import importlib.util
import json
import os

import numpy as np
import pytest
from test_round import BASE, _setup

from commefficient_tpu.data import FedSampler
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.telemetry.spans import PhaseSpans
from commefficient_tpu.telemetry.trace import (
    STAGES,
    CriticalPath,
    ProfilerWindow,
    cohort_trace_id,
    parse_profile_rounds,
    round_trace_id,
    step_of_trace_id,
    trace_round_scalars,
    trace_scalar_keys,
    write_run_report,
)
from commefficient_tpu.utils.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# both client banks live (the writeback path has work to do)
KW = dict(mode="local_topk", error_type="local", local_momentum=0.9, k=30)


def _script(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "scripts", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _lr_fn(step):
    return 0.3 - 0.01 * step


# ---------------------------------------------------------------------------
# trace ids
# ---------------------------------------------------------------------------

def test_trace_id_grammar_and_inverse():
    assert round_trace_id(7) == "r7"
    assert cohort_trace_id(3) == "c3"
    assert step_of_trace_id("r7") == 7
    assert step_of_trace_id(round_trace_id(0)) == 0
    # cohort ids and garbage do NOT invert to a step
    for bad in ("c3", "r", "r-1x", "", None, "x7"):
        assert step_of_trace_id(bad) is None


def test_trace_stage_taxonomy_pinned_to_checker():
    """The checker keeps a deliberate copy of the taxonomy (it imports
    nothing from the package); this pin is what keeps the two tuples
    from drifting apart."""
    assert tuple(_script("check_telemetry_schema").TRACE_STAGES) == \
        tuple(STAGES)


# ---------------------------------------------------------------------------
# CriticalPath: pure interval arithmetic
# ---------------------------------------------------------------------------

def _ev(name, ts, dur, step, collective=False, **args):
    return {"name": name, "ph": "X", "ts": ts, "dur": dur, "pid": 0,
            "tid": 0, "args": {"step": step, "fenced": False,
                               "collective": collective, **args}}


def test_critical_path_exclusive_disjoint_decomposition():
    """The worked example from the module docstring: exposed collective
    is assigned first, the collective-tagged dispatch span's UNEXPOSED
    part charges to dispatch (priority above h2d), and the exclusive
    times sum to exactly the wall-clock."""
    cp = CriticalPath([
        _ev("device_put", 0, 1000, 4),
        _ev("round_dispatch", 500, 2000, 4, collective=True),
        _ev("metric_drain", 2500, 500, 4),
    ])
    bd = cp.round_breakdown(4)
    assert bd["step"] == 4
    assert bd["wall_ms"] == pytest.approx(3.0)
    sm = bd["stages_ms"]
    assert sm["collective"] == pytest.approx(1.5)  # [1000, 2500) exposed
    assert sm["dispatch"] == pytest.approx(0.5)    # [500, 1000) unexposed
    assert sm["h2d"] == pytest.approx(0.5)         # [0, 500) left over
    assert sm["drain"] == pytest.approx(0.5)
    assert sm["data"] == sm["writeback"] == sm["idle"] == 0.0
    assert sum(sm.values()) == pytest.approx(bd["wall_ms"])
    assert bd["critical_stage"] == "collective"


def test_critical_path_idle_remainder_and_non_path_exclusion():
    """Un-spanned wall-clock lands in idle, and the retroactive
    async_buffer_residency span (which OVERLAPS many rounds by design)
    never stretches the round window or double-charges a stage."""
    cp = CriticalPath([
        _ev("data_load", 0, 1000, 1),
        _ev("checkpoint", 2000, 1000, 1),
        _ev("async_buffer_residency", 0, 50_000, 1),
    ])
    bd = cp.round_breakdown(1)
    assert bd["wall_ms"] == pytest.approx(3.0)  # not 50
    assert bd["stages_ms"]["data"] == pytest.approx(1.0)
    assert bd["stages_ms"]["drain"] == pytest.approx(1.0)
    assert bd["stages_ms"]["idle"] == pytest.approx(1.0)
    assert sum(bd["stages_ms"].values()) == pytest.approx(3.0)
    # rounds with no events decompose to None, never to a zeros row
    assert cp.round_breakdown(2) is None
    assert cp.steps() == [1]


def test_trace_round_scalars_constant_keys_and_zeros_row():
    zeros = trace_round_scalars(None, 5)
    assert set(zeros) == set(trace_scalar_keys())
    assert zeros["trace/critical_stage"] == float(STAGES.index("idle"))
    assert all(v == 0.0 for k, v in zeros.items()
               if k != "trace/critical_stage")
    # a negative step (the lagged emission's first rounds) is the zeros
    # row even with a live ring attached
    spans = PhaseSpans(".")
    with spans.span("round_dispatch", step=3):
        pass
    assert trace_round_scalars(spans, -1) == zeros
    live = trace_round_scalars(spans, 3)
    assert set(live) == set(trace_scalar_keys())
    assert sum(v for k, v in live.items()
               if k.endswith("_exclusive_ms")) > 0.0


# ---------------------------------------------------------------------------
# --profile_rounds window
# ---------------------------------------------------------------------------

def test_parse_profile_rounds_grammar():
    assert parse_profile_rounds("3-5") == (3, 5)
    assert parse_profile_rounds("7-7") == (7, 7)
    for bad in ("", "5-3", "3", "a-b", "-1-2", "3-"):
        with pytest.raises(ValueError):
            parse_profile_rounds(bad)


def test_profiler_window_clamps_fences_and_disarms(tmp_path):
    """A 0-1 spec cannot trace compile+warmup: the start clamps to
    MIN_WARMUP_STEPS, entry/exit are fenced, and after the window the
    profiler is permanently disarmed (exactly one capture per run)."""
    from commefficient_tpu.utils.profiling import MIN_WARMUP_STEPS

    fences = []
    win = ProfilerWindow("0-1", str(tmp_path),
                         fence_fn=lambda: fences.append(1))
    assert win.start == MIN_WARMUP_STEPS
    assert win.stop_at == MIN_WARMUP_STEPS + 2
    for s in range(MIN_WARMUP_STEPS):
        win.step(s)
    assert not fences and not win._active
    win.step(win.start)  # entry: fence, then start (or disarm off-TPU)
    assert len(fences) == 1
    assert win._active or not win._armed
    was_active = win._active
    win.step(win.stop_at)
    assert not win._active
    assert not win._armed  # one-shot either way
    if was_active:
        assert len(fences) == 2  # exit fenced too
    win.close()  # idempotent after the window closed itself

    # resume shifts the window past the restart's own warmup
    w2 = ProfilerWindow("5-6", str(tmp_path))
    w2.resume_at(10)
    assert w2.start == 10 + MIN_WARMUP_STEPS
    assert w2.stop_at == w2.start + 2
    # an empty logdir never arms
    w3 = ProfilerWindow("3-4", "")
    w3.step(3)
    assert not w3._active and not w3._armed


# ---------------------------------------------------------------------------
# e2e: pipelined + hosted clientstore — ids on every plane, then the
# full report chain (write_run_report -> checker -> analyze_run CLI)
# ---------------------------------------------------------------------------

def test_pipelined_hosted_trace_ids_and_run_report(tmp_path):
    from commefficient_tpu.pipeline.engine import PipelinedRounds

    cfg = Config(**{**KW, **BASE}, client_store="host", pipeline_depth=2,
                 telemetry_level=1)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    spans = PhaseSpans(str(tmp_path))
    sess.spans = spans
    eng = PipelinedRounds(cfg, sess, sampler, _lr_fn, num_rounds=6,
                          steps_per_epoch=6, spans=spans).start(0)
    try:
        ms = [m for _s, _lr, m in eng.epoch_rounds(0, 0)]
    finally:
        eng.close()
    assert sess.retrace_sentinel.retraces == 0
    sess.close_client_store()  # flush: writeback spans must be recorded
    path = spans.close()
    sess.spans = None

    # the lagged trace/* scalars ride every round's metrics with a
    # constant key set; the first two rounds are the zeros row
    keys = set(trace_scalar_keys())
    for m in ms:
        assert keys <= set(m)
    idle_ix = float(STAGES.index("idle"))
    assert ms[0]["trace/critical_stage"] == idle_ix
    assert all(ms[0][k] == 0.0 for k in keys
               if k.endswith("_exclusive_ms"))
    # round 2's metrics describe round 0 — real spans, nonzero wall
    assert sum(ms[2][k] for k in keys if k.endswith("_exclusive_ms")) > 0
    assert 0 <= int(ms[2]["trace/critical_stage"]) < len(STAGES)

    # v11 spans dump validates; every prefetch/gather/writeback span
    # carries the OWNING round's id (flush spans carry none by design)
    rec = _script("check_telemetry_schema").validate_spans(path)
    evs = [e for e in rec["traceEvents"] if e["ph"] == "X"]
    by_name = {}
    for e in evs:
        by_name.setdefault(e["name"], []).append(e)
    for name in ("prefetch_realize", "prefetch_stage",
                 "clientstore_gather", "clientstore_writeback",
                 "round_dispatch"):
        group = by_name.get(name, [])
        assert group, f"no {name} spans recorded"
        for e in group:
            assert e["args"].get("trace_id") == \
                round_trace_id(e["args"]["step"]), \
                f"{name} span not stamped with its round's trace id"
    for e in by_name.get("clientstore_flush", []):
        assert "trace_id" not in e["args"]
    # prefetch realizes every round once; writebacks cover every round
    assert sorted({e["args"]["step"]
                   for e in by_name["prefetch_realize"]}) == list(range(6))
    assert sorted({e["args"]["step"]
                   for e in by_name["clientstore_writeback"]}) == \
        list(range(6))

    # report chain: write -> checker invariants -> CLI
    out = write_run_report(str(tmp_path), generated_by="tests/test_trace")
    assert out and os.path.basename(out) == "run_report.json"
    rep = _script("check_telemetry_schema").validate_run_report(out)
    assert rep["rounds_analyzed"] == 6
    for r in rep["rounds"]:
        tot = sum(r["stages_ms"].values())
        assert tot <= r["wall_ms"] + max(1e-6, 1e-6 * r["wall_ms"])
    # the CLI re-derives the same report and ends stdout with the
    # machine-readable summary line (gate-script contract)
    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = _script("analyze_run").main([str(tmp_path)])
    assert rc == 0
    summary = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert summary == {"kind": "analyze_run", "run_dirs": 1,
                       "reports": 1, "failures": []}


# ---------------------------------------------------------------------------
# e2e: async engine (C = 3) — cohort ids with round parents
# ---------------------------------------------------------------------------

def test_async_spans_carry_cohort_trace_ids(tmp_path):
    from commefficient_tpu.asyncfed import AsyncFederation

    cfg = Config(async_buffer=4, async_concurrency=3,
                 staleness_exponent=0.5, arrival_rate=2.0,
                 mode="uncompressed", telemetry_level=1, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    spans = PhaseSpans(str(tmp_path))
    sess.spans = spans
    eng = AsyncFederation(cfg, sess, sampler, _lr_fn, 6,
                          steps_per_epoch=6, spans=spans).start()
    try:
        ms = [m for _s, _lr, m in eng.epoch_rounds(0, 0)]
    finally:
        eng.close()
    path = spans.close()
    sess.spans = None
    assert len(ms) == 6 and sess.retrace_sentinel.retraces == 0

    rec = _script("check_telemetry_schema").validate_spans(path)
    evs = [e for e in rec["traceEvents"] if e["ph"] == "X"]
    launches = [e for e in evs if e["name"] == "async_launch"]
    assert len(launches) >= 2
    cohorts = set()
    for e in launches:
        tid, parent = e["args"]["trace_id"], e["args"]["parent"]
        # every launch is on the cohort's own trace, parented by the
        # server round (= launch version) that realized it
        assert tid.startswith("c") and step_of_trace_id(tid) is None
        assert parent == round_trace_id(int(parent[1:]))
        cohorts.add(tid)
    assert len(cohorts) == len(launches)  # each cohort launches once
    applies = [e for e in evs if e["name"] == "async_apply"]
    assert applies
    for e in applies:
        assert e["args"]["trace_id"] == round_trace_id(e["args"]["step"])
    resid = [e for e in evs if e["name"] == "async_buffer_residency"]
    assert resid, "retired cohorts must leave a residency span"
    for e in resid:
        assert e["args"]["trace_id"] in cohorts
        assert e["args"]["parent"].startswith("r")


# ---------------------------------------------------------------------------
# level-0 discipline: tracing never touches the traced program
# ---------------------------------------------------------------------------

def test_level0_hlo_byte_identical_with_tracing_armed():
    """Trace ids, spans, and the profiler window are host-side only: at
    telemetry level 0 the lowered round HLO is byte-identical between a
    bare session and one with a spans ring attached AND a
    --profile_rounds window configured."""
    import jax.numpy as jnp

    texts = {}
    for armed in (False, True):
        cfg = Config(mode="uncompressed", telemetry_level=0,
                     profile_rounds="3-4" if armed else "", **BASE)
        ds, params, loss_fn = _setup(cfg.num_clients)
        sess = FederatedSession(cfg, params, loss_fn)
        if armed:
            sess.spans = PhaseSpans(".")
        sampler = FedSampler(ds, num_workers=cfg.num_workers,
                             local_batch_size=cfg.local_batch_size, seed=1)
        ids, batch = sampler.sample_round(0)
        texts[armed] = sess.round_fn.lower(
            sess.state, jnp.asarray(ids),
            {k: jnp.asarray(v) for k, v in batch.items()},
            jnp.float32(0.2),
        ).as_text()
    assert texts[False] == texts[True]


def test_hosted_rung_switch_with_tracing_zero_retraces(tmp_path):
    """The PR 17 hosted-ladder pin, with the v11 tracing active: a rung
    switch under a hosted store with spans attached still reuses the
    prewarmed programs — zero retraces — and the gather/writeback spans
    keep their round ids across the switch."""
    from commefficient_tpu.control import build_controller

    cfg = Config(**BASE, mode="local_topk", error_type="local",
                 local_momentum=0.9, topk_method="threshold",
                 client_store="host", telemetry_level=1,
                 control_policy="fixed", control_schedule="0-1=0,2-=1",
                 ladder="k=30,15")
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ctrl = build_controller(cfg, sess, num_rounds=4)
    ctrl.prewarm(sampler, 0.2)
    spans = PhaseSpans(str(tmp_path))
    sess.spans = spans
    for r in range(4):
        spans.step(r)
        ids, batch = sampler.sample_round(r)
        sess.train_round(ids, batch, 0.2)
    assert ctrl.switches == 1 and sess.active_rung == 1
    assert sess.retrace_sentinel.retraces == 0
    sess.close_client_store()
    path = spans.close()
    sess.spans = None
    with open(path) as f:
        evs = [e for e in json.load(f)["traceEvents"] if e["ph"] == "X"]
    stamped = [e for e in evs if e["name"] in
               ("clientstore_gather", "clientstore_writeback")]
    assert {e["args"]["step"] for e in stamped} == set(range(4))
    for e in stamped:
        assert e["args"]["trace_id"] == round_trace_id(e["args"]["step"])
