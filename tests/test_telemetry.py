"""Telemetry subsystem tests: in-graph diagnostics, comm ledger, flight
recorder — on the virtual 8-device CPU mesh (PR 3 acceptance).

Level-0 bit-parity with pre-telemetry rounds is carried by the EXISTING
golden recordings (tests/test_compress_parity.py runs default configs,
telemetry_level=0); here the complementary claims are pinned: level 0
traces NOTHING (HLO smoke test keyed on the sentinel's ``is_finite`` op —
the only such op in the round), levels only OBSERVE (final params match
across levels), the ledger's cumulative bytes are exact per mode, and a
NaN injection produces a flight record naming the first bad round plus a
raised DivergenceError.
"""

import glob
import json
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.data import FedDataset, FedSampler
from commefficient_tpu.models.losses import classification_loss
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.telemetry import CommLedger, DivergenceError, FlightRecorder
from commefficient_tpu.utils.config import Config
from commefficient_tpu.utils.logging import MetricsWriter, drain_round_metrics


class TinyMLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(4)(x)


BASE = dict(num_clients=12, num_workers=8, num_devices=8, local_batch_size=4,
            weight_decay=0.0, seed=5)

MODE_CONFIGS = {
    "sketch": dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                   k=40, num_rows=3, num_cols=256),
    "local_topk": dict(mode="local_topk", error_type="local", k=30,
                       local_momentum=0.9),
    "powersgd": dict(mode="powersgd", error_type="virtual",
                     virtual_momentum=0.9, powersgd_rank=2),
}


def _setup(num_clients=12, n=400):
    rng = np.random.default_rng(0)
    w = rng.normal(size=(8, 4))
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.normal(size=(n, 4)), axis=1).astype(np.int32)
    ds = FedDataset({"x": x, "y": y}, num_clients, iid=True, seed=0)
    model = TinyMLP()
    params = model.init(jax.random.key(0), jnp.zeros((1, 8)))
    return ds, params, classification_loss(model.apply)


def _one_round(cfg, lr=0.2):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    ids, batch = sampler.sample_round(0)
    return sess, sess.train_round(ids, batch, lr)


# ---------------------------------------------------------------------------
# in-graph diagnostics
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", sorted(MODE_CONFIGS))
def test_level2_emits_diag_scalars(mode):
    cfg = Config(telemetry_level=2, **MODE_CONFIGS[mode], **BASE)
    _, m = _one_round(cfg)
    for key in ("diag/grad_norm", "diag/update_norm",
                "diag/ef_residual_norm", "diag/ef_residual_max",
                "diag/nonfinite"):
        assert key in m, f"{mode}: missing {key}"
        assert np.isfinite(float(np.asarray(m[key])))
    assert float(np.asarray(m["diag/nonfinite"])) == 0.0
    fidelity = {"sketch": "diag/sketch_est_rel_err",
                "powersgd": "diag/powersgd_recon_rel_err"}.get(mode)
    if fidelity:
        assert fidelity in m and float(np.asarray(m[fidelity])) >= 0.0


def test_level0_emits_nothing():
    cfg = Config(telemetry_level=0, **MODE_CONFIGS["sketch"], **BASE)
    _, m = _one_round(cfg)
    assert not any(k.startswith("diag/") for k in m)


def test_uncompressed_update_norm_is_lr_times_grad_norm():
    """Dense SGD sanity anchor: delta = lr * agg, so the two norms are in
    exact ratio lr — pins both scalars to their documented semantics."""
    lr = 0.2
    cfg = Config(mode="uncompressed", telemetry_level=1, **BASE)
    _, m = _one_round(cfg, lr=lr)
    g = float(np.asarray(m["diag/grad_norm"]))
    u = float(np.asarray(m["diag/update_norm"]))
    np.testing.assert_allclose(u, lr * g, rtol=1e-5)


def test_sketch_fidelity_vanishes_with_huge_table():
    """The round-trip estimation error must -> 0 when the table dwarfs d
    (no collisions to mis-estimate) and be materially larger for a tight
    table — the scalar really tracks sketch fidelity."""
    big = Config(telemetry_level=2, **{**MODE_CONFIGS["sketch"],
                                       "num_cols": 8192}, **BASE)
    small = Config(telemetry_level=2, **{**MODE_CONFIGS["sketch"],
                                         "num_cols": 64}, **BASE)
    _, mb = _one_round(big)
    _, ms = _one_round(small)
    err_big = float(np.asarray(mb["diag/sketch_est_rel_err"]))
    err_small = float(np.asarray(ms["diag/sketch_est_rel_err"]))
    assert err_big < 0.05
    assert err_small > 2 * err_big


def test_telemetry_levels_do_not_change_training():
    """Diagnostics are observers: final params after several rounds match
    across levels (level 0 vs pre-PR bit-parity is carried by the golden
    recordings in test_compress_parity.py)."""
    finals = []
    for lvl in (0, 2):
        cfg = Config(telemetry_level=lvl, **MODE_CONFIGS["sketch"], **BASE)
        ds, params, loss_fn = _setup()
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
        for r in range(4):
            ids, batch = sampler.sample_round(r)
            sess.train_round(ids, batch, 0.2)
        finals.append(np.asarray(sess.state.params_vec))
    np.testing.assert_allclose(finals[0], finals[1], atol=1e-6)


def test_level0_hlo_free_of_diagnostic_ops():
    """The non-finite sentinel is the round's ONLY ``is_finite`` op, so its
    absence from the lowered HLO proves the whole telemetry block was
    dead-code-eliminated (never traced) at level 0 — and its presence at
    level >= 1 proves the marker detects what it claims to."""
    texts = {}
    for lvl in (0, 1):
        cfg = Config(telemetry_level=lvl, **MODE_CONFIGS["sketch"], **BASE)
        ds, params, loss_fn = _setup()
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
        ids, batch = sampler.sample_round(0)
        lowered = sess.round_fn.lower(
            sess.state, jnp.asarray(ids),
            {k: jnp.asarray(v) for k, v in batch.items()}, jnp.float32(0.2),
        )
        texts[lvl] = lowered.as_text()
    assert "is_finite" not in texts[0]
    assert "is_finite" in texts[1]


def test_fsdp_round_emits_diag_scalars():
    cfg = Config(fsdp=True, telemetry_level=1, topk_method="threshold",
                 **{**MODE_CONFIGS["sketch"]}, **BASE)
    _, m = _one_round(cfg)
    for key in ("diag/grad_norm", "diag/update_norm",
                "diag/ef_residual_norm", "diag/nonfinite"):
        assert key in m
        assert np.isfinite(float(np.asarray(m[key])))
    # sketch-mode grad_norm has the SAME semantics on both parallelism
    # paths: the AMS estimate from the psum'd table (the FSDP body reuses
    # fsdp_update's own sketch, no dense reduction added) — so the two
    # rounds' estimates agree to reduction-order noise
    repl = Config(telemetry_level=1, **MODE_CONFIGS["sketch"], **BASE)
    _, mr = _one_round(repl)
    g_fsdp = float(np.asarray(m["diag/grad_norm"]))
    g_ams = float(np.asarray(mr["diag/grad_norm"]))
    np.testing.assert_allclose(g_ams, g_fsdp, rtol=1e-3)


# ---------------------------------------------------------------------------
# comm ledger + flight recorder through the REAL train loop
# ---------------------------------------------------------------------------

def _train_loop_run(cfg, tmp_path, n=160, num_epochs=1):
    """Run cv_train.train_loop end-to-end on the TinyMLP task (the loop is
    workload-agnostic); returns (run_dir, steps_per_epoch * num_epochs)."""
    from commefficient_tpu.train.cv_train import train_loop

    ds, params, loss_fn = _setup(cfg.num_clients, n=n)
    test_ds = FedDataset({"x": ds.data["x"][:40], "y": ds.data["y"][:40]},
                         1, seed=0)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    run_dir = str(tmp_path / f"run_{cfg.mode}")
    writer = MetricsWriter(run_dir, cfg=cfg)
    try:
        train_loop(cfg, sess, sampler, test_ds, writer, eval_batch_size=32)
    finally:
        writer.close()
    return run_dir, sampler.steps_per_epoch() * num_epochs, sess


@pytest.mark.parametrize("mode", sorted(MODE_CONFIGS))
def test_comm_ledger_cumulative_bytes_exact(mode, tmp_path):
    """PR-3 acceptance: comm_ledger.json cumulative bytes == the mode's
    bytes_per_round x rounds EXACTLY (sketch, local_topk, powersgd)."""
    cfg = Config(telemetry_level=1, num_epochs=1, pivot_epoch=1,
                 lr_scale=0.1, **MODE_CONFIGS[mode], **BASE)
    run_dir, rounds, sess = _train_loop_run(cfg, tmp_path)
    with open(os.path.join(run_dir, "comm_ledger.json")) as f:
        ledger = json.load(f)
    bpr = sess.bytes_per_round()
    assert ledger["rounds"] == rounds
    assert ledger["cum_up_bytes"] == rounds * bpr["upload_bytes"]
    assert ledger["cum_down_bytes"] == rounds * bpr["download_bytes"]
    assert ledger["cum_bytes"] == (
        ledger["cum_up_bytes"] + ledger["cum_down_bytes"]
    )
    assert ledger["mode"] == mode
    # and the per-step comm scalars rode metrics.jsonl
    names = set()
    with open(os.path.join(run_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "name" in rec:
                names.add(rec["name"])
    assert {"comm/up_bytes", "comm/cum_bytes", "comm/cum_up_bytes",
            "train/loss", "diag/grad_norm", "diag/ef_residual_norm"} <= names


def test_divergence_raises_and_dumps_flight(tmp_path):
    """Seeded NaN injection: poison the params between rounds; the next
    drain must dump flight_<step>.json naming the FIRST bad round and raise
    DivergenceError instead of training onward on NaNs."""
    cfg = Config(telemetry_level=1, flight_window=8,
                 **MODE_CONFIGS["sketch"], **BASE)
    ds, params, loss_fn = _setup()
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    run_dir = str(tmp_path / "nanrun")
    writer = MetricsWriter(run_dir, cfg=cfg)
    ledger = CommLedger(sess.bytes_per_round(), mode=cfg.mode,
                        num_workers=cfg.num_workers)
    flight = FlightRecorder(cfg, logdir=run_dir)
    pending = []
    for r in range(2):  # two healthy rounds
        ids, batch = sampler.sample_round(r)
        pending.append((r, 0.2, sess.train_round(ids, batch, 0.2)))
    # the injection: a single NaN parameter — round 2 is the first bad one
    sess.state = sess.state._replace(
        params_vec=sess.state.params_vec.at[0].set(jnp.nan)
    )
    for r in range(2, 4):
        ids, batch = sampler.sample_round(r)
        pending.append((r, 0.2, sess.train_round(ids, batch, 0.2)))
    with pytest.raises(DivergenceError) as ei:
        drain_round_metrics(pending, writer, lambda loss, m: None,
                            ledger=ledger, flight=flight)
    writer.close()
    assert ei.value.step == 2, "must name the FIRST non-finite round"
    path = os.path.join(run_dir, "flight_2.json")
    assert os.path.exists(path) and ei.value.path == path
    with open(path) as f:
        rec = json.load(f)
    assert rec["first_bad_step"] == 2
    steps = [r["step"] for r in rec["records"]]
    assert steps == [0, 1, 2], "trajectory must include the healthy prefix"
    # healthy prefix really was healthy; the bad round is marked
    assert rec["records"][0]["scalars"]["diag/nonfinite"] == 0.0
    assert rec["records"][-1]["scalars"]["diag/nonfinite"] == 1.0
    # and the buffer was cleared + scalars flushed despite the raise
    assert pending == []


def test_train_loop_surfaces_divergence(tmp_path):
    """The full train loop path: a blow-up lr drives training non-finite
    within the epoch; the loop must raise DivergenceError (not return NaN
    val metrics) and leave a matching flight record in the run dir."""
    cfg = Config(telemetry_level=1, num_epochs=1, pivot_epoch=1,
                 lr_scale=1e24, mode="uncompressed", **BASE)
    with pytest.raises(DivergenceError) as ei:
        _train_loop_run(cfg, tmp_path)
    flights = glob.glob(str(tmp_path / "run_uncompressed" / "flight_*.json"))
    assert flights, "divergence must leave a flight record"
    with open(flights[0]) as f:
        rec = json.load(f)
    assert rec["first_bad_step"] == ei.value.step


def test_flight_on_exception_dumps_trajectory(tmp_path):
    flight = FlightRecorder(Config(telemetry_level=1, **BASE),
                            logdir=str(tmp_path))
    flight.record(0, 0.1, {"train/loss": 1.0})
    flight.record(1, 0.1, {"train/loss": 0.9})
    path = flight.on_exception(RuntimeError("boom"))
    assert os.path.exists(path)
    with open(path) as f:
        rec = json.load(f)
    assert rec["first_bad_step"] is None
    assert "RuntimeError: boom" in rec["reason"]
    assert [r["step"] for r in rec["records"]] == [0, 1]


def test_flight_ring_buffer_bounded():
    flight = FlightRecorder(window=4, logdir="")
    for s in range(10):
        flight.record(s, 0.1, {"train/loss": 1.0})
    assert [r["step"] for r in flight.records] == [6, 7, 8, 9]


# ---------------------------------------------------------------------------
# cv_train end-to-end (PR-3 acceptance: the real entry, telemetry_level=2)
# ---------------------------------------------------------------------------

def _run_cv_main(tmp_path, **mode_kw):
    from commefficient_tpu.train.cv_train import main as cv_main

    cv_main(
        [],
        dataset_name="femnist",
        model="resnet9",
        telemetry_level=2,
        num_clients=6,
        num_workers=4,
        num_devices=4,
        local_batch_size=32,
        num_epochs=1,
        pivot_epoch=1,
        lr_scale=0.05,
        dataset_dir=str(tmp_path),
        logdir=str(tmp_path / "runs"),
        seed=0,
        **mode_kw,
    )
    run_dirs = glob.glob(str(tmp_path / "runs" / "*"))
    assert len(run_dirs) == 1
    names = set()
    with open(os.path.join(run_dirs[0], "metrics.jsonl")) as f:
        header = json.loads(f.readline())
        assert header["type"] == "header"
        assert header["config"]["telemetry_level"] == 2
        for line in f:
            rec = json.loads(line)
            if "name" in rec:
                names.add(rec["name"])
    with open(os.path.join(run_dirs[0], "comm_ledger.json")) as f:
        ledger = json.load(f)
    assert ledger["cum_up_bytes"] == (
        ledger["rounds"] * ledger["bytes_per_round"]["upload_bytes"]
    )
    assert ledger["rounds"] > 0
    return names


@pytest.mark.slow  # ~52s ResNet-9 compile: tier-1 budget (PR 18) — the
# level-2 scalar surface stays tier-1 via the TinyMLP tests above, and
# cv_train e2e + validate_run_dir via test_train_entry/test_fedsim
def test_cv_train_telemetry_level2_end_to_end(tmp_path):
    """The real CLI->Config->round->drain->ledger path at --telemetry_level
    2 (local_topk: the cheapest CPU mode at ResNet-9 scale — the per-mode
    diag/fidelity + ledger-exactness coverage for sketch/powersgd runs
    in-tier on the TinyMLP task above; the sketch-mode entry run is the
    slow-marked twin below)."""
    names = _run_cv_main(tmp_path, mode="local_topk", error_type="local",
                         k=2000)
    assert {"diag/grad_norm", "diag/ef_residual_norm",
            "diag/ef_residual_max", "diag/nonfinite", "comm/up_bytes",
            "comm/cum_bytes", "train/loss", "lr", "val/loss"} <= names


@pytest.mark.slow  # the d=6.6M CountSketch einsum costs minutes on a 1-core
# CPU host; the sketch-mode telemetry algebra itself is pinned in-tier by
# the TinyMLP tests above (emission, fidelity, ledger exactness, HLO)
def test_cv_train_telemetry_sketch_end_to_end(tmp_path):
    names = _run_cv_main(tmp_path, mode="sketch", error_type="virtual",
                         virtual_momentum=0.9, k=2000, num_rows=3,
                         num_cols=300_000)
    assert {"diag/grad_norm", "diag/ef_residual_norm",
            "diag/sketch_est_rel_err", "comm/up_bytes",
            "comm/cum_bytes", "train/loss", "lr", "val/loss"} <= names
