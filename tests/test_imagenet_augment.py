"""ImageNet random-resized-crop augmenter: three-path equivalence.

The plan-based ImageNetAugment (data/imagenet.py) mirrors CifarAugment's
contract: ``plan`` draws the randomness once, and the numpy ``apply``, the
native C++ ``gather_apply`` kernel, and the traced ``device_apply`` realize
the same batch. Bilinear interpolation is float arithmetic, so the native
and XLA paths may differ from numpy by FMA contraction — pinned here to
<= 1 uint8 LSB on a small fraction of pixels (the CIFAR paths stay
bit-exact; they are pure copies).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu import native
from commefficient_tpu.data.fed_dataset import FedDataset
from commefficient_tpu.data.imagenet import ImageNetAugment, RRCPlan
from commefficient_tpu.data.sampler import FedSampler


def _toy(n=40, h=48, w=48, c=3, seed=0, uint8=True):
    rng = np.random.default_rng(seed)
    if uint8:
        return rng.integers(0, 256, size=(n, h, w, c)).astype(np.uint8)
    return rng.normal(size=(n, h, w, c)).astype(np.float32)


def test_plan_boxes_valid_and_deterministic():
    aug = ImageNetAugment()
    p = aug.plan(np.random.default_rng(3), 500, 48, 48)
    assert (p.hs >= 1).all() and (p.ws >= 1).all()
    assert (p.ys >= 0).all() and (p.xs >= 0).all()
    assert (p.ys + p.hs <= 48).all() and (p.xs + p.ws <= 48).all()
    # torchvision-style: area fractions spread well below 1 (real crops)
    assert (p.hs * p.ws < 0.9 * 48 * 48).sum() > 100
    p2 = aug.plan(np.random.default_rng(3), 500, 48, 48)
    for a, b in zip(p, p2):
        np.testing.assert_array_equal(a, b)


def test_plan_fallback_full_image():
    """Impossible aspect ratios exhaust all attempts -> torchvision's
    fallback, which for square sources is the full image."""
    aug = ImageNetAugment(scale=(1.0, 1.0), ratio=(3.0, 3.0))
    p = aug.plan(np.random.default_rng(0), 16, 32, 32)
    np.testing.assert_array_equal(p.hs, 32)
    np.testing.assert_array_equal(p.ws, 32)
    np.testing.assert_array_equal(p.ys, 0)
    np.testing.assert_array_equal(p.xs, 0)


def test_identity_crop_is_identity():
    """A full-image crop box resized to the same size must reproduce the
    input exactly (the bilinear grid then lands on integer coordinates)."""
    aug = ImageNetAugment()
    x = _toy(n=8)
    n = x.shape[0]
    p = RRCPlan(
        ys=np.zeros(n, np.int32), xs=np.zeros(n, np.int32),
        hs=np.full(n, 48, np.int32), ws=np.full(n, 48, np.int32),
        flips=np.zeros(n, bool),
    )
    np.testing.assert_array_equal(aug.apply(x, p), x)


def test_flip_semantics():
    aug = ImageNetAugment()
    x = _toy(n=4)
    n = x.shape[0]
    base = RRCPlan(
        ys=np.zeros(n, np.int32), xs=np.zeros(n, np.int32),
        hs=np.full(n, 48, np.int32), ws=np.full(n, 48, np.int32),
        flips=np.zeros(n, bool),
    )
    flipped = base._replace(flips=np.ones(n, bool))
    np.testing.assert_array_equal(
        aug.apply(x, flipped), aug.apply(x, base)[:, :, ::-1]
    )


@pytest.mark.parametrize("uint8", [True, False])
def test_device_apply_matches_numpy(uint8):
    aug = ImageNetAugment()
    x = _toy(n=32, uint8=uint8)
    p = aug.plan(np.random.default_rng(5), 32, 48, 48)
    want = aug.apply(x, p)
    got = np.asarray(aug.device_apply(jnp.asarray(x), *map(jnp.asarray, p)))
    if uint8:
        diff = np.abs(got.astype(np.int32) - want.astype(np.int32))
        assert diff.max() <= 1, f"max LSB diff {diff.max()}"
        assert (diff > 0).mean() < 0.05  # only rounding-edge pixels
    else:
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-5)


@pytest.mark.skipif(not native.available(), reason="no native lib")
@pytest.mark.parametrize("uint8", [True, False])
def test_native_gather_rrc_matches_numpy(uint8):
    aug = ImageNetAugment()
    data = _toy(n=64, uint8=uint8)
    rng = np.random.default_rng(9)
    idx = rng.integers(0, 64, size=48).astype(np.int64)
    p = aug.plan(rng, 48, 48, 48)
    got = native.gather_rrc(data, idx, p)
    want = aug.apply(np.ascontiguousarray(data[idx]), p)
    assert got.dtype == data.dtype
    if uint8:
        diff = np.abs(got.astype(np.int32) - want.astype(np.int32))
        assert diff.max() <= 1, f"max LSB diff {diff.max()}"
        assert (diff > 0).mean() < 0.05
    else:
        np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-5)


@pytest.mark.skipif(not native.available(), reason="no native lib")
def test_native_gather_rrc_bounds_check():
    aug = ImageNetAugment()
    data = _toy(n=8)
    idx = np.arange(4, dtype=np.int64)
    p = aug.plan(np.random.default_rng(0), 4, 48, 48)
    bad = p._replace(ys=p.ys + 48)  # box bottom beyond the image
    with pytest.raises(IndexError):
        native.gather_rrc(data, idx, bad)


def test_fused_sampler_round_with_rrc():
    """The fused sampler path (native or numpy-fallback) must agree with a
    hand-computed gather+apply on the same rng stream."""
    rng = np.random.default_rng(1)
    ds = FedDataset(
        {"x": _toy(n=256), "y": rng.integers(0, 10, 256).astype(np.int32)},
        8, seed=1,
    )
    aug = ImageNetAugment()
    s = FedSampler(ds, num_workers=4, local_batch_size=8, seed=3, augment=aug)
    assert s.fusable
    ids, batch = s.sample_round(0)
    # replay the identical draw sequence
    rng2 = np.random.default_rng((3, 0))
    clients = rng2.choice(8, size=4, replace=False)
    np.testing.assert_array_equal(ids, clients.astype(np.int32))
    flat = np.concatenate(
        [ds.client_batch_indices(int(c), 8, rng2) for c in clients]
    )
    p = aug.plan(rng2, 32, 48, 48)
    want = aug.apply(np.ascontiguousarray(ds.data["x"][flat]), p)
    got = batch["x"].reshape(32, 48, 48, 3)
    diff = np.abs(got.astype(np.int32) - want.astype(np.int32))
    assert diff.max() <= 1  # native path may differ by FMA rounding
