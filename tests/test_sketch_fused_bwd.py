"""Sketch-FUSED backward (cfg.sketch_fused_bwd; parallel/round.py
make_sketch_grad_one + ops/countsketch.py sketch_grad_tap).

The claim under pin: in sketch mode with the fused backward, the flat
[D] gradient — ``make_grad_one``'s ``ravel_pytree`` concat, a ~500 MB
transient at GPT-2 scale — is NEVER materialized. Per-leaf custom_vjp
taps sketch each cotangent into the table where AD produces it, and by
linearity the accumulated table equals the sketch of the full flat
gradient. Pinned here:

  * ops-level: the tap-accumulated table == ``sketch_segment`` of the
    reference per-leaf grads == (within scatter-order rounding) the
    matmul-path sketch of the concatenated grad;
  * HLO: the compiled fused-backward round carries the
    ``sketch_fused_bwd`` scope and NO ``flat_grad_concat`` scope (the
    marker ``make_grad_one`` wraps around its ravel_pytree) — while the
    default sketch round carries the concat marker (marker validity);
  * round-level: training parity vs the default dense-grad sketch round
    (same hash mapping, different summation order — tight tolerance),
    weight decay included (it composes as one matmul-path params
    sketch);
  * config: every incompatible knob is refused at construction with the
    blocker named.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from test_round import BASE, _setup

from commefficient_tpu.data import FedSampler
from commefficient_tpu.ops.countsketch import (
    CountSketch,
    sketch_grad_tap,
    sketch_segment,
    sketch_sparse,
    sketch_vec,
)
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.utils.config import Config


# ---------------------------------------------------------------------------
# ops level: the tap IS the sketch of the gradient
# ---------------------------------------------------------------------------

def test_tap_accumulates_sketch_of_full_gradient():
    spec = CountSketch(d=48, c=32, r=3, seed=3)
    rng = np.random.default_rng(0)
    a = jnp.asarray(rng.normal(size=(4, 4)).astype(np.float32))  # 16
    b = jnp.asarray(rng.normal(size=(32,)).astype(np.float32))   # 32
    x = jnp.asarray(rng.normal(size=(4,)).astype(np.float32))

    def loss(leaves):
        aa, bb = leaves
        return jnp.sum(jnp.sin(aa) * x[None, :]) + jnp.sum(bb * bb)

    def tapped(table):
        aa = sketch_grad_tap(spec, 0, a, table)
        bb = sketch_grad_tap(spec, 16, b, table)
        return loss((aa, bb))

    table = jax.grad(tapped)(jnp.zeros(spec.table_shape, jnp.float32))
    ga, gb = jax.grad(loss)((a, b))
    want = np.asarray(sketch_segment(spec, 0, ga)) + np.asarray(
        sketch_segment(spec, 16, gb)
    )
    np.testing.assert_allclose(np.asarray(table), want, rtol=0, atol=1e-6)
    # and the per-leaf segment sum IS the sketch of the concat (same
    # hash mapping as sketch_sparse over the full index range)
    flat = jnp.concatenate([ga.reshape(-1), gb.reshape(-1)])
    full = np.asarray(
        sketch_sparse(spec, jnp.arange(48, dtype=jnp.uint32), flat)
    )
    np.testing.assert_allclose(want, full, rtol=0, atol=1e-6)
    # matmul-path cross-check (summation order differs -> tolerance)
    mm = np.asarray(sketch_vec(spec, flat))
    scale = max(np.abs(mm).max(), 1.0)
    np.testing.assert_allclose(want, mm, rtol=0, atol=1e-5 * scale)


def test_tap_forward_is_identity():
    spec = CountSketch(d=8, c=8, r=1, seed=3)
    leaf = jnp.arange(8.0)
    out = sketch_grad_tap(spec, 0, leaf, jnp.zeros(spec.table_shape))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(leaf))


# ---------------------------------------------------------------------------
# round level: parity + the HLO concat pin
# ---------------------------------------------------------------------------

def _cfg(**kw):
    return Config(**{**BASE, "mode": "sketch", "error_type": "virtual",
                     "virtual_momentum": 0.9, "k": 40, "num_rows": 3,
                     "num_cols": 256, "topk_method": "threshold",
                     "fuse_clients": True, "weight_decay": 1e-4, **kw})


def _run(cfg, n_rounds=4):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, 0.2)
    return sess, float(np.asarray(m["loss"]))


def test_fused_bwd_training_parity_with_dense_grad_path():
    """Same rounds, same data: the fused backward's params track the
    default dense-grad sketch round to summation-order rounding —
    weight decay on (it must compose via the params sketch)."""
    s_dense, l_dense = _run(_cfg())
    s_fused, l_fused = _run(_cfg(sketch_fused_bwd=True))
    p_d = np.asarray(s_dense.state.params_vec)
    p_f = np.asarray(s_fused.state.params_vec)
    scale = max(np.abs(p_d).max(), 1.0)
    np.testing.assert_allclose(p_f, p_d, rtol=0, atol=5e-5 * scale)
    assert abs(l_fused - l_dense) < 1e-3


def test_fused_bwd_hlo_free_of_flat_grad_concat():
    """The acceptance pin: the fused-backward round's compiled HLO holds
    the sketch_fused_bwd scope and NO flat_grad_concat scope; the default
    round holds the concat marker (proving the marker is live)."""
    ds, params, loss_fn = _setup(12)
    sampler_cfg = _cfg(sketch_fused_bwd=True)
    sess = FederatedSession(sampler_cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=sampler_cfg.num_workers,
                         local_batch_size=sampler_cfg.local_batch_size,
                         seed=1)
    ids, batch = sampler.sample_round(0)
    ids_d = jnp.asarray(ids)
    text = sess.round_fn.lower(
        sess.state, ids_d, jax.tree.map(jnp.asarray, batch),
        jnp.float32(0.2),
    ).compile().as_text()
    assert "sketch_fused_bwd" in text
    assert "flat_grad_concat" not in text, (
        "the fused-backward round materialized the flat [D] grad concat"
    )
    sess2 = FederatedSession(_cfg(), params, loss_fn)
    text2 = sess2.round_fn.lower(
        sess2.state, ids_d, jax.tree.map(jnp.asarray, batch),
        jnp.float32(0.2),
    ).compile().as_text()
    assert "flat_grad_concat" in text2, "concat marker lost its validity"
    assert "sketch_fused_bwd" not in text2


def test_fused_bwd_composes_with_bf16_tables():
    s_fused, l = _run(_cfg(sketch_fused_bwd=True,
                           sketch_table_dtype="bfloat16"))
    assert np.isfinite(l)
    assert s_fused.state.momentum.dtype == jnp.bfloat16


# ---------------------------------------------------------------------------
# config gates: every blocker refused at construction, named
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kw,needle", [
    (dict(mode="true_topk"), "mode"),
    (dict(fuse_clients=False), "fuse_clients"),
    (dict(local_momentum=0.5), "local_momentum"),
    (dict(max_grad_norm=1.0), "max_grad_norm"),
    (dict(dp_noise_multiplier=0.1), "DP noise"),
    (dict(availability="bernoulli", dropout_prob=0.3), "fedsim"),
])
def test_fused_bwd_incompatible_knobs_refused(kw, needle):
    base = dict(BASE, mode="sketch", error_type="virtual", k=40,
                num_rows=3, num_cols=256, topk_method="threshold",
                fuse_clients=True, sketch_fused_bwd=True)
    base.update(kw)
    with pytest.raises((ValueError, NotImplementedError), match=needle):
        Config(**base)
