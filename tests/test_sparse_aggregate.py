"""Sparse allreduce collective layer (ISSUE 14): O(W*k) on-mesh aggregation.

The top-k modes' device transmits are k-sparse, yet the replicated round
aggregated them with a dense [D] psum. ``ops/collectives/`` exchanges
fixed-size (idx, val) pair buffers instead — ``sparse_allreduce`` (compact
-> pair all_gather -> scatter-add, replicated result) for local_topk's
``aggregate='auto'`` path, a reduce-scatter + workers-sharded server
algebra + W*k candidate gather for true_topk's explicit sparse path, and
the recursive-halving ``ppermute`` schedule (``sparse_allreduce_sharded``)
as the sharded-output primitive. Pinned here, on the virtual 8-device CPU
mesh:

  * sparse == dense-psum final params at atol 1e-6 per mode, across error
    modes, momentum, dampening, fedsim masking (+ all-dropped freeze),
    and offloaded client state;
  * the pair-exchange primitives' contracts (dense-sum equivalence,
    capacity-overflow drop semantics, duplicate-coordinate accumulation,
    the power-of-two schedule guard) and ``compact_nonzero`` edge cases
    (satellite: all-zero, > k nonzeros, k=0, tied magnitudes);
  * compiled-HLO traffic: the sparse round moves NO all-reduce/all-gather
    of >= O(D) elements (a [D] reduce-scatter is legal: O(D/W) per link,
    sharded result); the dense round's three per-round psums are FUSED
    into one all-reduce (satellite: tuple-psum fusion, op-count pinned);
  * defaults stay bit-untouched: ``aggregate='auto'`` on a 1-device mesh
    lowers to byte-identical HLO vs explicit dense;
  * the session audit reports the resolved path + pair-exchange bound
    (schema v7) and scripts/check_telemetry_schema.py accepts the
    artifact (rejection self-tests live in tests/test_telemetry_schema.py);
  * zero retraces across sparse rounds (the AOT-prewarm contract).
"""

import re
import warnings

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from test_round import BASE, _final_vec, _run, _setup

from commefficient_tpu.data import FedSampler
from commefficient_tpu.ops.collectives import (
    all_gather_pairs,
    scatter_add_pairs,
    sparse_allreduce,
    sparse_allreduce_sharded,
)
from commefficient_tpu.ops.topk import compact_nonzero
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.parallel.mesh import WORKERS, make_mesh
from commefficient_tpu.utils.config import Config
from commefficient_tpu.utils.jax_compat import shard_map

P = jax.sharding.PartitionSpec

LOCAL = dict(mode="local_topk", k=7, topk_method="threshold")
TRUE = dict(mode="true_topk", k=9, topk_method="threshold")

# the error/momentum corners the sparse aggregation must agree with the
# dense psum on, per mode (dampening masks on the UNSCALED selection —
# the lr=0 corner is pinned separately below)
LOCAL_CASES = {
    "none": dict(error_type="none"),
    "local_err": dict(error_type="local"),
    "local_err_vel": dict(error_type="local", local_momentum=0.9),
    "local_err_rho": dict(error_type="local", virtual_momentum=0.9),
}
TRUE_CASES = {
    "none": dict(error_type="none"),
    "none_rho": dict(error_type="none", virtual_momentum=0.9),
    "virtual": dict(error_type="virtual"),
    "virtual_rho": dict(error_type="virtual", virtual_momentum=0.9),
    "virtual_decay": dict(error_type="virtual", virtual_momentum=0.9,
                          error_decay=0.9),
    "virtual_dampen": dict(error_type="virtual", virtual_momentum=0.9,
                           momentum_dampening=True),
}


# -- parity: sparse aggregation IS the dense psum ------------------------

@pytest.mark.parametrize("name", sorted(LOCAL_CASES))
def test_local_topk_sparse_matches_dense(name):
    kw = {**LOCAL, **LOCAL_CASES[name]}
    sd, ld = _run(Config(aggregate="dense", **kw, **BASE), n_rounds=4)
    ss, ls = _run(Config(aggregate="sparse", **kw, **BASE), n_rounds=4)
    np.testing.assert_allclose(ls, ld, rtol=1e-6,
                               err_msg=f"{name}: losses drifted")
    np.testing.assert_allclose(
        _final_vec(ss), _final_vec(sd), atol=1e-6,
        err_msg=f"{name}: sparse aggregation is NOT the dense psum",
    )


def test_local_topk_auto_is_sparse_and_matches():
    """auto on the multi-device threshold round resolves sparse and runs
    the same program as explicit sparse (local_topk opts in for auto: its
    sparse path changes no state shapes and no server algebra)."""
    kw = {**LOCAL, "error_type": "local"}
    sa, _ = _run(Config(**kw, **BASE), n_rounds=3)
    ss, _ = _run(Config(aggregate="sparse", **kw, **BASE), n_rounds=3)
    assert sa.aggregate_resolved == "sparse"
    np.testing.assert_array_equal(_final_vec(sa), _final_vec(ss))


@pytest.mark.parametrize("name", sorted(TRUE_CASES))
def test_true_topk_sparse_matches_dense(name):
    kw = {**TRUE, **TRUE_CASES[name]}
    sd, ld = _run(Config(aggregate="dense", **kw, **BASE), n_rounds=4)
    ss, ls = _run(Config(aggregate="sparse", **kw, **BASE), n_rounds=4)
    np.testing.assert_allclose(ls, ld, rtol=1e-6,
                               err_msg=f"{name}: losses drifted")
    np.testing.assert_allclose(
        _final_vec(ss), _final_vec(sd), atol=1e-6,
        err_msg=f"{name}: sharded-state aggregation is NOT the dense round",
    )


def test_true_topk_sparse_dampening_lr_zero_round():
    """error_type='none' + dampening at lr == 0 (a warmup round): the
    mask must come from the UNSCALED selection on the sharded slice too,
    or the twins' momentum diverges from round 1."""
    kw = {**TRUE, "error_type": "none", "virtual_momentum": 0.9,
          "momentum_dampening": True}
    finals, moms = [], []
    for agg in ("dense", "sparse"):
        cfg = Config(aggregate=agg, **kw, **BASE)
        ds, params, loss_fn = _setup(cfg.num_clients)
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
        for r, lr in enumerate((0.0, 0.3, 0.3)):
            ids, batch = sampler.sample_round(r)
            sess.train_round(ids, batch, lr)
        finals.append(_final_vec(sess))
        # the sparse rung's momentum is the [dp] workers-sharded vector;
        # D == dp at this geometry would hide a padding bug, so slice
        moms.append(np.asarray(sess.state.momentum)[:sess.grad_size])
    np.testing.assert_allclose(moms[1], moms[0], atol=1e-6,
                               err_msg="momentum diverged at the lr=0 round")
    np.testing.assert_allclose(finals[1], finals[0], atol=1e-6)


def test_local_topk_sparse_offload_matches_hbm():
    """The offloaded-client-state round threads the pair exchange
    identically (client rows ride host RAM; aggregation is on-mesh)."""
    kw = {**LOCAL, "error_type": "local", "local_momentum": 0.9,
          "aggregate": "sparse"}
    s_hbm, _ = _run(Config(**kw, **BASE), n_rounds=3)
    s_off, _ = _run(Config(offload_client_state=True, **kw, **BASE),
                    n_rounds=3)
    np.testing.assert_allclose(_final_vec(s_off), _final_vec(s_hbm),
                               atol=1e-6)


# -- fedsim masking rides the sparse paths unchanged ---------------------

def _masked_run(mode_kw, env, n_rounds=3):
    from test_sketch_decode import _cohort_env  # noqa: F401 (re-export use)

    cfg = Config(availability="bernoulli", dropout_prob=0.5, **mode_kw,
                 **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    m = None
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, 0.3, env=env)
    return sess, sampler, m


@pytest.mark.parametrize("mode_kw", [
    {**LOCAL, "error_type": "local"},
    {**TRUE, "error_type": "virtual", "virtual_momentum": 0.9},
], ids=["local_topk", "true_topk"])
def test_fedsim_masked_sparse_matches_dense(mode_kw):
    """Masking is pre-encode and the live renormalization is a scalar on
    the aggregate, so both commute with the pair exchange."""
    from test_sketch_decode import _cohort_env

    S = [0, 2, 3, 5, 7]
    sd, _, _ = _masked_run({**mode_kw, "aggregate": "dense"},
                           _cohort_env(S))
    ss, _, m = _masked_run({**mode_kw, "aggregate": "sparse"},
                           _cohort_env(S))
    assert m["fedsim/participation_rate"] == len(S) / 8
    np.testing.assert_allclose(_final_vec(ss), _final_vec(sd), atol=1e-6)


def test_fedsim_all_dropped_round_freezes_sparse_state():
    """Zero live clients under true_topk sparse aggregation: the gathered
    candidate VALUES zero out and the workers-sharded momentum/error
    leaves carry forward — the all-dropped guard must hold for sharded
    server state exactly as it does replicated."""
    from test_sketch_decode import _cohort_env

    kw = {**TRUE, "error_type": "virtual", "virtual_momentum": 0.9,
          "aggregate": "sparse"}
    ss, sampler, _ = _masked_run(kw, _cohort_env([0, 2, 3, 5, 7]))
    before = _final_vec(ss).copy()
    mom = np.asarray(ss.state.momentum).copy()
    err = np.asarray(ss.state.error).copy()
    ids, batch = sampler.sample_round(5)
    m = ss.train_round(ids, batch, 0.3, env=_cohort_env([]))
    assert m["fedsim/all_dropped"] == 1.0
    assert np.array_equal(before, _final_vec(ss))
    assert np.array_equal(mom, np.asarray(ss.state.momentum))
    assert np.array_equal(err, np.asarray(ss.state.error))
    assert np.isfinite(float(m["loss"]))


# -- resolution + validation ---------------------------------------------

def test_auto_resolution_and_validation():
    """auto = sparse only where it is a pure aggregation swap: local_topk
    on a multi-device threshold round. true_topk/sketch re-home server
    state / reroute error feedback, so they engage on explicit opt-in
    only; invalid combinations fail at Config time."""
    ds, params, loss_fn = _setup()
    sess = FederatedSession(
        Config(**LOCAL, error_type="local", **BASE), params, loss_fn)
    assert sess.aggregate_resolved == "sparse"
    # exact top-k pads its transmit densely -> stays dense
    sess = FederatedSession(
        Config(**{**LOCAL, "topk_method": "exact"}, error_type="local",
               **BASE), params, loss_fn)
    assert sess.aggregate_resolved == "dense"
    # single-device mesh: nothing to exchange -> dense
    sess = FederatedSession(
        Config(**LOCAL, error_type="local", **{**BASE, "num_devices": 1}),
        params, loss_fn)
    assert sess.aggregate_resolved == "dense"
    # true_topk/sketch: auto never flips them (explicit opt-in only)
    sess = FederatedSession(
        Config(**TRUE, error_type="virtual", **BASE), params, loss_fn)
    assert sess.aggregate_resolved == "dense"
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        sess = FederatedSession(
            Config(mode="sketch", k=40, num_rows=3, num_cols=256,
                   error_type="virtual", topk_method="threshold", **BASE),
            params, loss_fn)
    assert sess.aggregate_resolved == "dense"
    # Config-time validation
    with pytest.raises(ValueError, match="sparse transmit"):
        Config(mode="uncompressed", aggregate="sparse", **BASE)
    with pytest.raises(ValueError, match="fsdp"):
        Config(**TRUE, error_type="virtual", aggregate="sparse",
               fsdp=True, **BASE)
    with pytest.raises(ValueError, match="threshold"):
        Config(**{**TRUE, "topk_method": "exact"}, error_type="virtual",
               aggregate="sparse", **BASE)
    with pytest.raises(ValueError, match="auto|dense|sparse"):
        Config(**LOCAL, aggregate="bogus", **BASE)
    # degenerate explicit sparse on a 1-device mesh: works, but warns
    with pytest.warns(UserWarning, match="degenerate"):
        FederatedSession(
            Config(**LOCAL, error_type="local", aggregate="sparse",
                   **{**BASE, "num_devices": 1}),
            params, loss_fn)


# -- compiled-HLO traffic pins -------------------------------------------

def _compiled_round_text(cfg):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=4, seed=1)
    ids, batch = sampler.sample_round(0)
    args = [sess.state, jnp.asarray(ids),
            {k: jnp.asarray(v) for k, v in batch.items()}, jnp.float32(0.2)]
    if cfg.offload_client_state:
        ids_np = np.asarray(ids)
        args.append(jnp.asarray(sess.host_vel[ids_np])
                    if sess.host_vel is not None else ())
        args.append(jnp.asarray(sess.host_err[ids_np])
                    if sess.host_err is not None else ())
    return sess, sess.round_fn.lower(*args).compile().as_text()


def _collective_shapes(text, op):
    """(elems, line) per static ``op`` occurrence, skipping -done halves
    (the -start line carries an (operand, output, ...) tuple — take the
    transferred second component, as telemetry/xla_audit.py does)."""
    out = []
    for ln in text.splitlines():
        m = re.search(r"=\s*([^=]*?)\s*" + op + r"(-start)?\(", ln)
        if m is None:
            continue
        shapes = [int(np.prod([int(x) for x in dims.split(",") if x]))
                  for _, dims in re.findall(
                      r"([a-z]+[0-9]+[a-z0-9]*|pred)\[([\d,]*)\]",
                      m.group(1))]
        if m.group(2) and len(shapes) > 1:
            shapes = shapes[1:]
        out.append((sum(shapes), ln))
    return out


def test_hlo_sparse_round_moves_no_dense_collective():
    """THE acceptance pin: the compiled sparse round (client state
    offloaded — in-graph [C, D] rows have their own pre-existing
    writeback gather) contains no all-reduce or all-gather of >= O(D)
    elements; every exchange is <= the W*k pair bound (times w_loc for
    local_topk's per-client buffers)."""
    cases = [
        (Config(**LOCAL, error_type="local", offload_client_state=True,
                aggregate="sparse", **BASE),
         "sparse_allreduce", 8 * 1 * 7),
        (Config(**TRUE, error_type="virtual", virtual_momentum=0.9,
                aggregate="sparse", **BASE),
         "sparse_aggregate_decode", 8 * 9),
    ]
    for cfg, marker, pair_bound in cases:
        sess, text = _compiled_round_text(cfg)
        d = sess.grad_size
        assert pair_bound < d, "traffic claim trivial at this geometry"
        assert marker in text, f"named-scope marker {marker!r} missing"
        for op in ("all-reduce", "all-gather"):
            for elems, ln in _collective_shapes(text, op):
                assert elems <= pair_bound, (
                    f"{cfg.mode}: {op} of {elems} elements exceeds the "
                    f"pair-exchange bound {pair_bound} — a d-sized "
                    f"collective leaked in: {ln.strip()[:160]!r}"
                )


def test_hlo_true_topk_sparse_uses_reduce_scatter():
    """The dense transmit lands sharded via reduce-scatter (O(D/W) per
    link — the legal dense-payload collective), never via an all-reduce."""
    cfg = Config(**TRUE, error_type="virtual", aggregate="sparse", **BASE)
    _, text = _compiled_round_text(cfg)
    assert _collective_shapes(text, "reduce-scatter"), (
        "the sharded aggregation must lower to reduce-scatter"
    )


def test_hlo_dense_round_fuses_collectives_into_one_psum():
    """Satellite pin (tuple-psum fusion): the uncompressed dense round's
    agg + loss_mean + aux_sum reductions lower to exactly ONE all-reduce
    (concat-of-raveled-f32-leaves — bitwise the same sums, one launch)."""
    cfg = Config(mode="uncompressed", **BASE)
    _, text = _compiled_round_text(cfg)
    ars = _collective_shapes(text, "all-reduce")
    assert len(ars) == 1, (
        f"expected ONE fused all-reduce, found {len(ars)}: "
        + "; ".join(ln.strip()[:100] for _, ln in ars)
    )
    # and the local_topk DENSE round keeps the same fused shape
    cfg = Config(**LOCAL, error_type="local", aggregate="dense", **BASE)
    _, text = _compiled_round_text(cfg)
    assert len(_collective_shapes(text, "all-reduce")) == 1


def test_hlo_one_device_auto_is_bit_identical_to_dense():
    """Defaults stay untouched: on a 1-device mesh auto resolves dense and
    the lowered round is BYTE-identical to explicit dense."""
    base1 = {**BASE, "num_devices": 1, "num_workers": 1, "num_clients": 4}
    texts = {}
    for agg in (None, "dense"):
        kw = {} if agg is None else {"aggregate": agg}
        cfg = Config(**LOCAL, error_type="local", **kw, **base1)
        ds, params, loss_fn = _setup(4)
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=1, local_batch_size=4, seed=1)
        ids, batch = sampler.sample_round(0)
        texts[agg] = sess.round_fn.lower(
            sess.state, jnp.asarray(ids),
            {k: jnp.asarray(v) for k, v in batch.items()},
            jnp.float32(0.2),
        ).as_text()
        assert sess.aggregate_resolved == "dense"
    assert texts[None] == texts["dense"]


# -- audit + schema (producer side; checker rejections in
#    tests/test_telemetry_schema.py) -------------------------------------

def test_audit_reports_sparse_aggregate_and_checker_accepts(tmp_path):
    import importlib.util as iu
    import pathlib

    spec_ = iu.spec_from_file_location(
        "check_telemetry_schema",
        pathlib.Path(__file__).resolve().parents[1]
        / "scripts" / "check_telemetry_schema.py",
    )
    checker = iu.module_from_spec(spec_)
    spec_.loader.exec_module(checker)

    cfg = Config(**TRUE, error_type="virtual", aggregate="sparse", **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=8, local_batch_size=4, seed=1)
    ids, batch = sampler.sample_round(0)
    audit = sess.audit_compiled_round(np.asarray(ids), batch, 0.2)
    rep = audit.report(generated_by="test", cfg=cfg)
    assert rep["aggregate"] == "sparse"
    assert rep["collectives"]["sparse_agg_bound"] == 8 * TRUE["k"]
    assert rep["collectives"]["max_all_reduce_elems"] is not None
    path = audit.write(str(tmp_path), generated_by="test", cfg=cfg)
    checker.validate_perf_report(path)  # must not raise

    # the dense twin records aggregate='dense' with no bound
    cfg_d = Config(**TRUE, error_type="virtual", aggregate="dense", **BASE)
    sess_d = FederatedSession(cfg_d, params, loss_fn)
    rep_d = sess_d.audit_compiled_round(
        np.asarray(ids), batch, 0.2).report(generated_by="test")
    assert rep_d["aggregate"] == "dense"
    assert rep_d["collectives"]["sparse_agg_bound"] is None


def test_zero_retraces_across_sparse_rounds():
    """The sparse programs are as signature-stable as the dense ones: no
    silent retrace across rounds or the audit's AOT trace."""
    for kw in ({**LOCAL, "error_type": "local"},
               {**TRUE, "error_type": "virtual", "aggregate": "sparse"}):
        sess, _ = _run(Config(**kw, **BASE), n_rounds=4)
        assert sess.retrace_sentinel.retraces == 0, kw["mode"]


# -- pair-exchange primitive contracts -----------------------------------

def test_sparse_allreduce_matches_dense_sum():
    """compact -> pair all_gather -> scatter-add == the dense psum, for
    W k-sparse vectors with overlapping supports (duplicate coordinates
    accumulate)."""
    rng = np.random.default_rng(0)
    d, k, Wd = 257, 6, 8  # odd d: no accidental alignment
    dense = np.zeros((Wd, d), np.float32)
    for w in range(Wd):
        sup = rng.choice(d // 2, size=k, replace=False)  # forced overlap
        dense[w, sup] = rng.normal(size=k).astype(np.float32)
    mesh = make_mesh(Wd)
    f = shard_map(
        lambda v: sparse_allreduce(v[0], k, WORKERS)[None],
        mesh=mesh, in_specs=(P(WORKERS),), out_specs=P(WORKERS),
    )
    out = np.asarray(jax.jit(f)(jnp.asarray(dense)))
    want = dense.sum(axis=0)
    for w in range(Wd):  # replicated: every chip holds the full sum
        np.testing.assert_allclose(out[w], want, atol=1e-6)


def test_sparse_allreduce_sharded_matches_sum_then_slice():
    """The recursive-halving ppermute schedule: each chip ends with its
    balanced D/W slice of the global sum — psum-then-slice, without the
    psum."""
    rng = np.random.default_rng(1)
    d, k, Wd = 512, 5, 8
    dense = np.zeros((Wd, d), np.float32)
    for w in range(Wd):
        sup = rng.choice(d, size=k, replace=False)
        dense[w, sup] = rng.normal(size=k).astype(np.float32)
    mesh = make_mesh(Wd)
    f = shard_map(
        lambda v: sparse_allreduce_sharded(
            v[0], k, WORKERS, axis_size=Wd)[None],
        mesh=mesh, in_specs=(P(WORKERS),), out_specs=P(WORKERS),
    )
    out = np.asarray(jax.jit(f)(jnp.asarray(dense))).reshape(-1)
    np.testing.assert_allclose(out, dense.sum(axis=0), atol=1e-6)


def test_sparse_allreduce_sharded_lowers_to_ppermute_only():
    """The schedule's traffic claim: pure collective-permute HLO — no
    all-reduce, no all-gather, nothing replicated."""
    d, k, Wd = 512, 5, 8
    mesh = make_mesh(Wd)
    f = shard_map(
        lambda v: sparse_allreduce_sharded(
            v[0], k, WORKERS, axis_size=Wd)[None],
        mesh=mesh, in_specs=(P(WORKERS),), out_specs=P(WORKERS),
    )
    text = jax.jit(f).lower(
        jax.ShapeDtypeStruct((Wd, d), jnp.float32)).compile().as_text()
    assert "collective-permute" in text
    assert "all-reduce" not in text
    assert "all-gather" not in text


def test_sparse_allreduce_sharded_rejects_non_power_of_two():
    with pytest.raises(ValueError, match="power-of-two"):
        sparse_allreduce_sharded(jnp.zeros(16), 4, WORKERS, axis_size=6)


def test_all_gather_pairs_and_scatter_add_contracts():
    """all_gather_pairs flattens [W, cap] -> [W*cap] in axis order;
    scatter_add_pairs accumulates duplicate coordinates and treats
    (0, 0.0) padding as a no-op."""
    Wd = 8
    mesh = make_mesh(Wd)
    f = shard_map(
        lambda i, v: tuple(
            a[None] for a in all_gather_pairs(i[0], v[0], WORKERS)),
        mesh=mesh, in_specs=(P(WORKERS), P(WORKERS)),
        out_specs=(P(WORKERS), P(WORKERS)),
    )
    idx = jnp.arange(Wd * 3, dtype=jnp.int32).reshape(Wd, 3)
    val = jnp.asarray(np.arange(Wd * 3, dtype=np.float32).reshape(Wd, 3))
    g_idx, g_val = jax.jit(f)(idx, val)
    np.testing.assert_array_equal(np.asarray(g_idx[0]), np.arange(Wd * 3))
    np.testing.assert_array_equal(np.asarray(g_val[0]),
                                  np.arange(Wd * 3, dtype=np.float32))
    out = scatter_add_pairs(
        6, jnp.asarray([2, 2, 5, 0, 0], jnp.int32),
        jnp.asarray([1.0, 2.5, -1.0, 0.0, 0.0], jnp.float32),
    )
    np.testing.assert_allclose(np.asarray(out),
                               [0.0, 0.0, 3.5, 0.0, 0.0, -1.0])


def test_compact_nonzero_edge_cases():
    """Satellite: the contracts the pair exchange leans on, beyond
    test_sketch_decode's basic round-trip."""
    # > k nonzeros: the FIRST k by position are kept, the tail dropped —
    # documented drop semantics (the sparse capacity is a hard buffer)
    v = jnp.asarray([1.0, 0.0, 2.0, 3.0, 0.0, 4.0, 5.0])
    idx, val = compact_nonzero(v, 3)
    np.testing.assert_array_equal(np.asarray(idx), [0, 2, 3])
    np.testing.assert_array_equal(np.asarray(val), [1.0, 2.0, 3.0])
    # k = 0: a legal empty buffer, scatter-safe
    idx, val = compact_nonzero(v, 0)
    assert idx.shape == val.shape == (0,)
    np.testing.assert_allclose(
        np.asarray(jnp.zeros(7).at[idx].add(val)), np.zeros(7))
    # duplicate magnitudes (ties) are irrelevant to compaction: selection
    # happened upstream; compaction is positional and keeps BOTH
    v = jnp.asarray([0.0, 2.0, -2.0, 0.0, 2.0])
    idx, val = compact_nonzero(v, 4)
    np.testing.assert_array_equal(np.asarray(idx), [1, 2, 4, 0])
    np.testing.assert_array_equal(np.asarray(val), [2.0, -2.0, 2.0, 0.0])
    # all-zero input at k = capacity: pure padding
    idx, val = compact_nonzero(jnp.zeros(5), 5)
    assert not np.any(np.asarray(val)) and not np.any(np.asarray(idx))


def test_sparse_allreduce_capacity_overflow_drops_by_position():
    """More nonzeros than the declared capacity: compact keeps the first
    ``capacity`` by position — the exchange NEVER silently grows. (In the
    round this cannot trigger: local_topk's transmit has <= w_loc*k
    nonzeros by construction and capacity is exactly w_loc*k.)"""
    Wd = 8
    mesh = make_mesh(Wd)
    v = jnp.ones((Wd, 16), jnp.float32)  # 16 nonzeros, capacity 4
    f = shard_map(
        lambda x: sparse_allreduce(x[0], 4, WORKERS)[None],
        mesh=mesh, in_specs=(P(WORKERS),), out_specs=P(WORKERS),
    )
    out = np.asarray(jax.jit(f)(v))[0]
    np.testing.assert_allclose(out[:4], 8.0)  # first 4 coords survive
    np.testing.assert_allclose(out[4:], 0.0)  # the tail is dropped
