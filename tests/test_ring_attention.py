"""Ring attention + sequence-parallel GPT-2: exactness vs the dense path
on a virtual seq-sharded mesh (the long-context capability extension)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.models.gpt2 import (
    GPT2Config,
    GPT2DoubleHeads,
    dense_causal_attention,
)
from commefficient_tpu.parallel.mesh import make_mesh
from commefficient_tpu.parallel.ring_attention import ring_attention_sharded
from commefficient_tpu.parallel.sequence import sp_gpt2_apply

B, H, T, HD = 2, 4, 64, 8


@pytest.fixture(scope="module")
def seq_mesh():
    return make_mesh(1, 1, 4)  # 4-way seq axis on the virtual 8-CPU pool


def _qkv(seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(B, H, T, HD)).astype(np.float32), dtype)
    return mk(), mk(), mk()


def test_ring_matches_dense_causal(seq_mesh):
    q, k, v = _qkv()
    dense = dense_causal_attention(q, k, v)
    ring = ring_attention_sharded(seq_mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense), atol=2e-5)


def test_ring_matches_dense_noncausal(seq_mesh):
    q, k, v = _qkv(1)

    def dense_full(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(jnp.float32(HD))
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)

    ring = ring_attention_sharded(seq_mesh, q, k, v, causal=False)
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense_full(q, k, v)), atol=2e-5
    )


def test_ring_gradients_match_dense(seq_mesh):
    q, k, v = _qkv(2)
    w = jnp.asarray(np.random.default_rng(3).normal(size=(B, H, T, HD)).astype(np.float32))

    def loss_dense(q, k, v):
        return jnp.sum(dense_causal_attention(q, k, v) * w)

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention_sharded(seq_mesh, q, k, v) * w)

    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gd, gr):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a), atol=3e-5)


@pytest.mark.slow  # integration variant; the ring kernel's exactness
# (fwd + grads) and the federated TP/SP round stay default-tier
def test_sp_gpt2_forward_matches_dense(seq_mesh):
    cfg = GPT2Config(vocab_size=128, n_positions=T, n_embd=32, n_layer=2,
                     n_head=4, dtype=jnp.float32)
    model = GPT2DoubleHeads(cfg)
    rng = np.random.default_rng(5)
    ids = jnp.asarray(rng.integers(0, 128, size=(2, 2, T)).astype(np.int32))
    tt = jnp.asarray(rng.integers(0, 128, size=(2, 2, T)).astype(np.int32))
    mc = jnp.asarray(rng.integers(0, T, size=(2, 2)).astype(np.int32))
    params = model.init(jax.random.key(0), ids, token_type_ids=tt, mc_token_ids=mc)

    lm_d, mc_d = model.apply(params, ids, token_type_ids=tt, mc_token_ids=mc)
    lm_s, mc_s = sp_gpt2_apply(seq_mesh, model, params, ids,
                               token_type_ids=tt, mc_token_ids=mc)
    np.testing.assert_allclose(np.asarray(lm_s), np.asarray(lm_d), atol=2e-4)
    np.testing.assert_allclose(np.asarray(mc_s), np.asarray(mc_d), atol=2e-4)


def test_sp_rejects_indivisible_sequence(seq_mesh):
    cfg = GPT2Config(vocab_size=64, n_positions=66, n_embd=16, n_layer=1,
                     n_head=2, dtype=jnp.float32)
    model = GPT2DoubleHeads(cfg)
    ids = jnp.zeros((1, 1, 66), jnp.int32)
    params = model.init(jax.random.key(0), ids)
    with pytest.raises(ValueError, match="divide"):
        sp_gpt2_apply(seq_mesh, model, params, ids)
