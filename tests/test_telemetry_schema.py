"""Writer <-> schema pinning for the telemetry artifacts.

Same pattern as tests/test_mode_dispatch.py: the checker script is loaded
from scripts/ and exercised in tier-1. Artifacts are produced through the
REAL writer classes (MetricsWriter, CommLedger, FlightRecorder), so a
writer format change that breaks the documented schema fails here — and
the rejection cases guard the checker against rotting into a vacuous
pass."""

import importlib.util
import json
import os

import pytest

from commefficient_tpu.telemetry import CommLedger, FlightRecorder
from commefficient_tpu.utils.config import Config
from commefficient_tpu.utils.logging import MetricsWriter

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(REPO, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _write_run(tmp_path, rounds=3):
    """A full artifact set through the real writers."""
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=10, num_rows=3, num_cols=64, telemetry_level=2)
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    ledger = CommLedger({"upload_floats": 192, "download_floats": 20,
                         "upload_bytes": 768, "download_bytes": 80},
                        mode="sketch", num_workers=8)
    flight = FlightRecorder(cfg, logdir=run_dir)
    for s in range(rounds):
        writer.scalar("train/loss", 1.0 / (s + 1), s)
        writer.scalar("lr", 0.1, s)
        writer.scalar("diag/grad_norm", 0.5, s)
        for k, v in ledger.on_round(s).items():
            writer.scalar(k, v, s)
        flight.record(s, 0.1, {"train/loss": 1.0 / (s + 1),
                               "diag/nonfinite": 0.0})
    writer.close()
    ledger.write(run_dir)
    flight.dump(rounds - 1, reason="test dump", first_bad_step=rounds - 1)
    return run_dir


def test_real_artifacts_validate(tmp_path):
    mod = _checker()
    out = mod.validate_run_dir(_write_run(tmp_path))
    kinds = {os.path.basename(p) for p in out}
    assert kinds == {"metrics.jsonl", "comm_ledger.json", "flight_2.json"}


def test_artifacts_from_real_drain_path_validate(tmp_path):
    """Review regression: the drain records the round's RAW metric dict
    into the flight ring (bare aux keys: loss, correct, ...) and writes a
    non-finite loss into metrics.jsonl — both must validate, through the
    REAL drain_round_metrics, not hand-crafted records."""
    import jax.numpy as jnp

    from commefficient_tpu.telemetry import DivergenceError
    from commefficient_tpu.utils.logging import drain_round_metrics

    cfg = Config(mode="uncompressed", telemetry_level=1)
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    flight = FlightRecorder(cfg, logdir=run_dir)
    pending = [
        (0, 0.1, {"loss": jnp.float32(1.0), "correct": jnp.float32(3.0),
                  "count": jnp.float32(4.0),
                  "diag/nonfinite": jnp.float32(0.0)}),
        (1, 0.1, {"loss": jnp.float32(float("nan")),
                  "correct": jnp.float32(0.0), "count": jnp.float32(4.0),
                  "diag/nonfinite": jnp.float32(1.0)}),
    ]
    with pytest.raises(DivergenceError):
        drain_round_metrics(pending, writer, lambda *a: None, flight=flight)
    writer.close()
    mod = _checker()
    out = mod.validate_run_dir(run_dir)
    assert {os.path.basename(p) for p in out} == {"metrics.jsonl",
                                                  "flight_1.json"}
    # the non-finite loss landed as a strict-JSON "nan" marker, not a bare
    # NaN token
    with open(os.path.join(run_dir, "metrics.jsonl")) as f:
        content = f.read()
    assert '"value": "nan"' in content and "NaN" not in content


def test_flight_with_nonfinite_lr_and_config_stays_strict_json(tmp_path):
    """Review regression: a non-finite lr or config float (a sweep-produced
    NaN lr_scale IS a divergence scenario) must not emit bare NaN tokens
    into the flight dump — jsonable_tree stringifies them and the artifact
    still validates."""
    import json as _json

    cfg = Config(mode="uncompressed", telemetry_level=1,
                 lr_scale=float("nan"))
    flight = FlightRecorder(cfg, logdir=str(tmp_path))
    flight.record(0, float("nan"), {"loss": 1.0})
    path = flight.dump(0, reason="nan lr", first_bad_step=0)
    content = open(path).read()
    assert "NaN" not in content  # strict JSON, markers only
    rec = _json.loads(content)
    assert rec["records"][0]["lr"] == "nan"
    assert rec["meta"]["config"]["lr_scale"] == "nan"
    mod = _checker()
    mod.validate_flight(path)


def test_checker_rejects_bare_nan_token(tmp_path):
    mod = _checker()
    run_dir = _write_run(tmp_path)
    with open(os.path.join(run_dir, "metrics.jsonl"), "a") as f:
        f.write('{"name": "train/loss", "value": NaN, "step": 9, "t": 0.0}\n')
    with pytest.raises(mod.SchemaError, match="bare NaN"):
        mod.validate_metrics_jsonl(os.path.join(run_dir, "metrics.jsonl"))


def test_checker_rejects_missing_header(tmp_path):
    mod = _checker()
    p = tmp_path / "metrics.jsonl"
    p.write_text('{"name": "train/loss", "value": 1.0, "step": 0, "t": 0}\n')
    with pytest.raises(mod.SchemaError, match="header"):
        mod.validate_metrics_jsonl(p)


def test_checker_rejects_unknown_scalar_namespace(tmp_path):
    mod = _checker()
    run_dir = _write_run(tmp_path)
    with open(os.path.join(run_dir, "metrics.jsonl"), "a") as f:
        f.write(json.dumps({"name": "bogus/thing", "value": 1.0,
                            "step": 9, "t": 0.0}) + "\n")
    with pytest.raises(mod.SchemaError, match="bogus/thing"):
        mod.validate_metrics_jsonl(os.path.join(run_dir, "metrics.jsonl"))


def test_checker_rejects_missing_walltime(tmp_path):
    mod = _checker()
    run_dir = _write_run(tmp_path)
    with open(os.path.join(run_dir, "metrics.jsonl"), "a") as f:
        f.write(json.dumps({"name": "train/loss", "value": 1.0,
                            "step": 9}) + "\n")
    with pytest.raises(mod.SchemaError, match="'t'"):
        mod.validate_metrics_jsonl(os.path.join(run_dir, "metrics.jsonl"))


def test_checker_enforces_ledger_exactness(tmp_path):
    """The checker itself enforces cum == rounds * bytes_per_round, so a
    drifted ledger writer cannot validate."""
    mod = _checker()
    run_dir = _write_run(tmp_path)
    path = os.path.join(run_dir, "comm_ledger.json")
    with open(path) as f:
        rec = json.load(f)
    rec["cum_up_bytes"] += 4
    rec["cum_bytes"] += 4
    with open(path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(mod.SchemaError, match="cum_up_bytes"):
        mod.validate_comm_ledger(path)


def test_checker_rejects_out_of_order_flight_records(tmp_path):
    mod = _checker()
    run_dir = _write_run(tmp_path)
    path = os.path.join(run_dir, "flight_2.json")
    with open(path) as f:
        rec = json.load(f)
    rec["records"] = rec["records"][::-1]
    with open(path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(mod.SchemaError, match="step order"):
        mod.validate_flight(path)


def test_checker_rejects_unknown_schema_version(tmp_path):
    mod = _checker()
    run_dir = _write_run(tmp_path)
    path = os.path.join(run_dir, "comm_ledger.json")
    with open(path) as f:
        rec = json.load(f)
    rec["schema_version"] = 999
    with open(path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(mod.SchemaError, match="schema_version"):
        mod.validate_comm_ledger(path)


class _FakeController:
    def snapshot(self):
        return {"policy": "fixed", "ladder": "k=20,10", "rung": 1,
                "num_rungs": 2, "switches": 1, "rounds_seen": 3,
                "last_switch_round": 2}


def test_flight_controller_block_validates_and_rejects(tmp_path):
    """v4: a controller-attached flight dump carries the dump-time
    controller snapshot; the checker validates it and rejects an
    out-of-range rung."""
    cfg = Config(mode="uncompressed", telemetry_level=1)
    flight = FlightRecorder(cfg, logdir=str(tmp_path),
                            controller=_FakeController())
    flight.record(0, 0.1, {"loss": 1.0})
    path = flight.dump(0, reason="test", first_bad_step=None)
    mod = _checker()
    rec = mod.validate_flight(path)
    assert rec["controller"]["rung"] == 1
    rec["controller"]["rung"] = 5  # outside num_rungs
    with open(path, "w") as f:
        json.dump(rec, f)
    with pytest.raises(mod.SchemaError, match="num_rungs"):
        mod.validate_flight(path)


def test_header_controller_block_validates_and_rejects(tmp_path):
    """v4: the metrics run-header carries the controller identity block
    (MetricsWriter extra_header); the checker validates it."""
    cfg = Config(mode="uncompressed", telemetry_level=1)
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg, extra_header={
        "controller": {"policy": "ef_feedback", "ladder": "k=20,10",
                       "rung": 1, "num_rungs": 2},
    })
    writer.scalar("control/rung", 1.0, 0)
    writer.close()
    mod = _checker()
    path = os.path.join(run_dir, "metrics.jsonl")
    mod.validate_metrics_jsonl(path)
    # a malformed block (missing policy) must fail
    with open(path) as f:
        lines = f.read().splitlines()
    header = json.loads(lines[0])
    del header["controller"]["policy"]
    with open(path, "w") as f:
        f.write(json.dumps(header) + "\n" + "\n".join(lines[1:]) + "\n")
    with pytest.raises(mod.SchemaError, match="policy"):
        mod.validate_metrics_jsonl(path)


def test_checker_rejects_unknown_control_scalar_only_outside_prefix(
        tmp_path):
    """control/ is a documented v4 prefix; names under it pass, the
    namespace boundary still rejects others."""
    mod = _checker()
    run_dir = _write_run(tmp_path)
    path = os.path.join(run_dir, "metrics.jsonl")
    with open(path, "a") as f:
        f.write(json.dumps({"name": "control/budget_remaining_bytes",
                            "value": 123.0, "step": 9, "t": 0.0}) + "\n")
    mod.validate_metrics_jsonl(path)


def test_cli_exit_codes(tmp_path):
    mod = _checker()
    run_dir = _write_run(tmp_path)
    assert mod.main([run_dir]) == 0
    (tmp_path / "empty").mkdir()
    assert mod.main([str(tmp_path / "empty")]) == 1


def test_cli_json_summary_always_last_line(tmp_path, capsys):
    """The gate-script consumer contract (established by
    scripts/check_bench_regression.py, now uniform across all gate
    scripts): the last stdout line is machine-readable JSON on EVERY
    exit path — pass, fail, and usage error."""
    mod = _checker()
    run_dir = _write_run(tmp_path)

    def last(capsys):
        return json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    assert mod.main([run_dir]) == 0
    s = last(capsys)
    assert s["kind"] == "telemetry_schema"
    assert s["run_dirs"] == 1 and s["artifacts"] >= 3
    assert s["failures"] == []

    (tmp_path / "empty2").mkdir()
    assert mod.main([str(tmp_path / "empty2")]) == 1
    s = last(capsys)
    assert s["failures"] and "no telemetry artifacts" in s["failures"][0]

    assert mod.main([]) == 2  # usage error still ends with the summary
    s = last(capsys)
    assert s["kind"] == "telemetry_schema" and "error" in s

    # a TRUNCATED artifact (raw JSONDecodeError, not SchemaError) must
    # fail the run dir and still end with the summary, not a traceback
    bad = tmp_path / "corrupt"
    bad.mkdir()
    (bad / "comm_ledger.json").write_text("{truncated")
    assert mod.main([str(bad)]) == 1
    s = last(capsys)
    assert s["failures"], "corrupt artifact must be reported in failures"


# ---------------------------------------------------------------------------
# v5: pipeline/* scalars + thread-aware spans
# ---------------------------------------------------------------------------

def test_v5_pipeline_scalars_validate_and_reject(tmp_path):
    """The pipeline/ scalar prefix is in-schema through the REAL writer;
    the occupancy-range and staged-rounds-integer invariants are enforced
    (tampered values rejected). The per-round-metric form is additionally
    pinned by tests/test_pipeline.py through the real engine."""
    mod = _checker()
    cfg = Config(mode="uncompressed", telemetry_level=1, pipeline_depth=2)
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    for s in range(3):
        writer.scalar("train/loss", 1.0, s)
        writer.scalar("lr", 0.1, s)
        writer.scalar("pipeline/occupancy", s / 2.0, s)
        writer.scalar("pipeline/host_stall_ms", 0.4, s)
        writer.scalar("pipeline/staged_rounds", float(s), s)
    writer.close()
    path = os.path.join(run_dir, "metrics.jsonl")
    assert mod.validate_metrics_jsonl(path) == 15
    lines = open(path).read().splitlines()
    for bad_rec, msg in [
        ({"name": "pipeline/occupancy", "value": -0.1, "step": 0,
          "t": 1.0}, "outside \\[0, 1\\]"),
        ({"name": "pipeline/occupancy", "value": 2.0, "step": 0,
          "t": 1.0}, "outside \\[0, 1\\]"),
        ({"name": "pipeline/staged_rounds", "value": 0.5, "step": 0,
          "t": 1.0}, "integer"),
        ({"name": "pipeline/staged_rounds", "value": -1.0, "step": 0,
          "t": 1.0}, "integer"),
        ({"name": "pipeline/host_stall_ms", "value": "nan", "step": 0,
          "t": 1.0}, "finite number"),
        # scan engine (sketch-gap PR): the block length is a count of
        # whole scanned rounds, >= 1 — fractional/zero values mean the
        # engine miscounted its block plan
        ({"name": "pipeline/scan_rounds_per_dispatch", "value": 2.5,
          "step": 0, "t": 1.0}, "positive integer"),
        ({"name": "pipeline/scan_rounds_per_dispatch", "value": 0.0,
          "step": 0, "t": 1.0}, "positive integer"),
    ]:
        bad = tmp_path / "bad.jsonl"
        bad.write_text(lines[0] + "\n" + json.dumps(bad_rec) + "\n")
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_metrics_jsonl(str(bad))


def test_v5_spans_thread_metadata_validates_and_rejects(tmp_path):
    """Thread-aware spans through the REAL recorder: lane tids + the
    thread_name metadata event validate; a non-thread_name metadata
    event, a negative tid, and a metadata-only dump are rejected."""
    import threading

    from commefficient_tpu.telemetry.spans import PhaseSpans

    mod = _checker()
    spans = PhaseSpans(str(tmp_path))
    spans.step(2)
    with spans.span("round_dispatch"):
        pass

    def worker():
        spans.register_lane("round-prefetch")
        with spans.span("prefetch_realize", step=3):
            pass

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    path = spans.close()
    rec = mod.validate_spans(path)
    lanes = {e["tid"] for e in rec["traceEvents"] if e["ph"] == "X"}
    assert lanes == {0, 1}
    meta = [e for e in rec["traceEvents"] if e["ph"] == "M"]
    assert [(e["tid"], e["args"]["name"]) for e in meta] == \
        [(1, "round-prefetch")]

    def tampered(mutate, msg):
        with open(path) as f:
            r = json.load(f)
        mutate(r)
        bad = os.path.join(str(tmp_path), "bad_spans.json")
        with open(bad, "w") as f:
            json.dump(r, f)
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_spans(bad)

    tampered(lambda r: r["traceEvents"].append(
        {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
         "args": {"name": "x"}}), "unknown metadata")
    tampered(lambda r: r["traceEvents"][0].update(tid=-1), "tid")
    tampered(lambda r: r.update(traceEvents=meta), "no complete")


def test_v5_spans_lane_labels_survive_ring_eviction(tmp_path):
    """Lane-label metadata must outlive the bounded span ring: a run long
    enough to wrap the ring many times still dumps the thread_name
    record, or long-run traces lose their track labels."""
    from commefficient_tpu.telemetry.spans import MAX_EVENTS, PhaseSpans

    mod = _checker()
    spans = PhaseSpans(str(tmp_path))
    spans.register_lane("main")
    spans.step(2)
    for _ in range(MAX_EVENTS + 10):  # wrap the ring past the label
        with spans.span("round_dispatch"):
            pass
    rec = mod.validate_spans(spans.close())
    meta = [e for e in rec["traceEvents"] if e["ph"] == "M"]
    assert [(e["tid"], e["args"]["name"]) for e in meta] == [(0, "main")]


# ---------------------------------------------------------------------------
# v6: resilience/* scalars + the flight recovery_history block
# ---------------------------------------------------------------------------

def test_v6_resilience_scalars_validate_and_reject(tmp_path):
    """The resilience/ scalar prefix is in-schema through the REAL
    writer; the counter/flag/rollback-round invariants are enforced
    (tampered values rejected)."""
    mod = _checker()
    cfg = Config(mode="uncompressed", telemetry_level=1,
                 recover_policy="retry")
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    for s in range(3):
        writer.scalar("train/loss", 1.0, s)
        writer.scalar("lr", 0.1, s)
        writer.scalar("resilience/recoveries", float(s > 1), s)
        writer.scalar("resilience/rollback_round", -1.0 if s < 2 else 1.0, s)
        writer.scalar("resilience/rung_demotions", 0.0, s)
        writer.scalar("resilience/blacklisted_clients", 0.0, s)
        writer.scalar("resilience/preempt_requested", 0.0, s)
    writer.close()
    path = os.path.join(run_dir, "metrics.jsonl")
    assert mod.validate_metrics_jsonl(path) == 21
    header = open(path).readline()
    for bad_rec, msg in [
        ({"name": "resilience/recoveries", "value": -1.0, "step": 0,
          "t": 1.0}, "non-negative integer"),
        ({"name": "resilience/recoveries", "value": 0.5, "step": 0,
          "t": 1.0}, "non-negative integer"),
        ({"name": "resilience/blacklisted_clients", "value": 1.5,
          "step": 0, "t": 1.0}, "non-negative integer"),
        ({"name": "resilience/preempt_requested", "value": 0.5, "step": 0,
          "t": 1.0}, "0/1 flag"),
        ({"name": "resilience/rollback_round", "value": -2.0, "step": 0,
          "t": 1.0}, ">= -1"),
        ({"name": "resilience/rollback_round", "value": 1.5, "step": 0,
          "t": 1.0}, ">= -1"),
        ({"name": "resilience/recoveries", "value": "nan", "step": 0,
          "t": 1.0}, "finite number"),
    ]:
        bad = tmp_path / "bad.jsonl"
        bad.write_text(header + json.dumps(bad_rec) + "\n")
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_metrics_jsonl(str(bad))


class _FakeResilienceRider:
    """Duck-typed the way FlightRecorder consumes it: a ``history``
    attribute holding the recovery entries."""

    def __init__(self, history):
        self.history = history


def test_v6_flight_recovery_history_validates_and_rejects(tmp_path):
    """A recovery-carrying flight dump (the _recovery-tagged sibling the
    manager writes) validates through the REAL recorder, and the checker
    rejects out-of-order ordinals, post-divergence rollback targets, and
    empty blocks."""
    mod = _checker()
    cfg = Config(mode="uncompressed", telemetry_level=1,
                 recover_policy="retry")
    flight = FlightRecorder(cfg, logdir=str(tmp_path))
    flight.resilience = _FakeResilienceRider([
        {"recovery": 1, "policy": "retry", "first_bad_step": 5,
         "reason": "diag/nonfinite", "outcome": "recovered",
         "rollback_to": 4},
        {"recovery": 2, "policy": "retry", "first_bad_step": 8,
         "reason": "diag/nonfinite", "outcome": "recovered",
         "rollback_to": 8},
    ])
    for s in range(3):
        flight.record(s, 0.1, {"loss": 1.0})
    path = flight.dump(5, reason="recovered from divergence at round 5",
                       first_bad_step=5, tag="_recovery")
    assert path.endswith("flight_5_recovery.json")
    rec = mod.validate_flight(path)
    assert len(rec["recovery_history"]) == 2

    def tampered(mutate, msg):
        with open(path) as f:
            r = json.load(f)
        mutate(r)
        bad = os.path.join(str(tmp_path), "bad_flight.json")
        with open(bad, "w") as f:
            json.dump(r, f)
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_flight(bad)

    tampered(lambda r: r["recovery_history"][1].update(recovery=3),
             "out of order")
    tampered(lambda r: r["recovery_history"][0].update(rollback_to=6),
             "pre-divergence")
    tampered(lambda r: r["recovery_history"][0].pop("policy"), "policy")
    tampered(lambda r: r.update(recovery_history=[]), "non-empty")
    tampered(lambda r: r["recovery_history"][0].update(first_bad_step=-1),
             "negative first_bad_step")


# ---------------------------------------------------------------------------
# v8: async/* scalars + the perf_report overlap-geometry block
# ---------------------------------------------------------------------------

def test_v8_async_scalars_validate_and_reject(tmp_path):
    """The async/ scalar prefix is in-schema through the REAL writer; the
    staleness-sign and integer-gauge invariants are enforced (tampered
    values rejected). The end-to-end form — these scalars riding a real
    asyncfed run's metrics.jsonl — is pinned by tests/test_asyncfed.py."""
    mod = _checker()
    cfg = Config(mode="uncompressed", telemetry_level=1, num_workers=8,
                 num_devices=8, async_buffer=4, async_concurrency=2,
                 staleness_exponent=0.5)
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    for s in range(3):
        writer.scalar("train/loss", 1.0, s)
        writer.scalar("lr", 0.1, s)
        writer.scalar("async/staleness_mean", 0.5 * s, s)
        writer.scalar("async/staleness_max", float(s), s)
        writer.scalar("async/buffer_fill", float(s), s)
        # 0 is legal: the run's trailing updates launch no replacement
        writer.scalar("async/concurrent_cohorts", float(2 - s), s)
        writer.scalar("async/effective_participation", 3.5, s)
    writer.close()
    path = os.path.join(run_dir, "metrics.jsonl")
    assert mod.validate_metrics_jsonl(path) == 21
    header = open(path).readline()
    for bad_rec, msg in [
        ({"name": "async/staleness_mean", "value": -0.5, "step": 0,
          "t": 1.0}, "negative"),
        ({"name": "async/staleness_max", "value": -1.0, "step": 0,
          "t": 1.0}, "negative"),
        ({"name": "async/effective_participation", "value": -3.5,
          "step": 0, "t": 1.0}, "negative"),
        ({"name": "async/buffer_fill", "value": 1.5, "step": 0,
          "t": 1.0}, "non-negative integer"),
        ({"name": "async/buffer_fill", "value": -1.0, "step": 0,
          "t": 1.0}, "non-negative integer"),
        ({"name": "async/concurrent_cohorts", "value": 0.5, "step": 0,
          "t": 1.0}, "non-negative integer"),
        ({"name": "async/concurrent_cohorts", "value": -1.0, "step": 0,
          "t": 1.0}, "non-negative integer"),
        ({"name": "async/staleness_mean", "value": "nan", "step": 0,
          "t": 1.0}, "finite number"),
    ]:
        bad = tmp_path / "bad.jsonl"
        bad.write_text(header + json.dumps(bad_rec) + "\n")
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_metrics_jsonl(str(bad))


def _write_perf_report(tmp_path, **extra):
    """A REAL audit-produced perf report on the TinyMLP round (the async
    variant exercises the engine='async' producer path end-to-end)."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp
    import numpy as np

    from commefficient_tpu.data import FedDataset, FedSampler
    from commefficient_tpu.models.losses import classification_loss
    from commefficient_tpu.parallel import FederatedSession

    class TinyMLP(nn.Module):
        @nn.compact
        def __call__(self, x):
            x = nn.relu(nn.Dense(16)(x))
            return nn.Dense(4)(x)

    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=20, num_rows=3, num_cols=200, telemetry_level=1,
                 num_clients=12, num_workers=8, num_devices=8,
                 local_batch_size=4, seed=5, **extra)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(200, 8)).astype(np.float32)
    y = rng.integers(0, 4, size=200).astype(np.int32)
    ds = FedDataset({"x": x, "y": y}, cfg.num_clients, iid=True, seed=0)
    model = TinyMLP()
    params = model.init(jax.random.key(0), jnp.zeros((1, 8)))
    sess = FederatedSession(cfg, params, classification_loss(model.apply))
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    ids, batch = sampler.sample_round(0)
    audit = sess.audit_compiled_round(ids, batch, 0.2)
    return audit.write(str(tmp_path), generated_by="test", cfg=cfg)


def test_v8_perf_report_async_block_required_and_forbidden(tmp_path):
    """A REAL async audit report validates with its overlap-geometry
    block; the checker rejects every mislabeling direction — block on a
    sync report, async engine without a block, and malformed geometry."""
    mod = _checker()
    path = _write_perf_report(tmp_path, async_buffer=4, async_concurrency=2,
                              staleness_exponent=0.5)
    rec = mod.validate_perf_report(path)
    assert rec["engine"] == "async"
    assert rec["async"] == {"buffer": 4, "concurrency": 2,
                            "staleness_exponent": 0.5}

    def tampered(mutate, msg):
        with open(path) as f:
            r = json.load(f)
        mutate(r)
        bad = os.path.join(str(tmp_path), "bad_report.json")
        with open(bad, "w") as f:
            json.dump(r, f)
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_perf_report(bad)

    tampered(lambda r: r.pop("async"), "missing required field 'async'")
    tampered(lambda r: r["async"].update(buffer=0), "below 1")
    tampered(lambda r: r["async"].update(concurrency=1.5),
             "must be an integer")
    tampered(lambda r: r["async"].update(staleness_exponent="x"),
             "non-numeric")
    tampered(lambda r: r["async"].update(staleness_exponent=-0.5),
             "below 0")
    tampered(lambda r: r.update(engine="bogus"), "unknown engine")
    # forbidden direction: the block riding a synchronous report
    tampered(lambda r: r.update(engine="replicated"),
             "present on a 'replicated' report")


# ---------------------------------------------------------------------------
# v9: the exposed-collective gauge + the perf-report overlap block
# ---------------------------------------------------------------------------

def test_v9_exposed_collective_scalar_validates_and_rejects(tmp_path):
    """xla/exposed_collective_ms through the REAL writer validates; the
    gauge invariant (finite, >= 0) rejects every tampering direction."""
    mod = _checker()
    cfg = Config(mode="uncompressed", telemetry_level=1)
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    for s in range(3):
        writer.scalar("train/loss", 1.0, s)
        writer.scalar("lr", 0.1, s)
        writer.scalar("xla/exposed_collective_ms", 0.25 * s, s)
    writer.close()
    path = os.path.join(run_dir, "metrics.jsonl")
    mod.validate_metrics_jsonl(path)

    lines = open(path).read().splitlines()
    for bad_rec, msg in [
        ({"name": "xla/exposed_collective_ms", "value": -0.5, "step": 0,
          "t": 1.0}, "negative"),
        ({"name": "xla/exposed_collective_ms", "value": "nan", "step": 0,
          "t": 1.0}, "finite number"),
        ({"name": "xla/exposed_collective_ms", "value": True, "step": 0,
          "t": 1.0}, "neither a number"),
    ]:
        bad = tmp_path / "bad.jsonl"
        bad.write_text(lines[0] + "\n" + json.dumps(bad_rec) + "\n")
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_metrics_jsonl(str(bad))


def test_v9_spans_collective_tag_and_exposure_field(tmp_path):
    """A REAL spans dump with collective-tagged spans validates and
    carries the dump-level exposure figure; the checker rejects a false
    tag and a negative exposure."""
    from commefficient_tpu.telemetry.spans import PhaseSpans

    mod = _checker()
    spans = PhaseSpans(str(tmp_path))
    spans.step(2)
    with spans.span("round_dispatch", collective=True):
        pass
    with spans.span("data_load"):
        pass
    path = spans.close()
    rec = mod.validate_spans(path)
    assert rec["exposed_collective_ms"] >= 0.0
    tagged = [e for e in rec["traceEvents"]
              if e["ph"] == "X" and e["args"].get("collective")]
    assert len(tagged) == 1 and tagged[0]["name"] == "round_dispatch"

    def tampered(mutate, msg):
        with open(path) as f:
            r = json.load(f)
        mutate(r)
        bad = os.path.join(str(tmp_path), "bad_spans.json")
        with open(bad, "w") as f:
            json.dump(r, f)
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_spans(bad)

    tampered(lambda r: r["traceEvents"][0]["args"].update(collective=False),
             "args.collective must be true")
    tampered(lambda r: r["traceEvents"][0]["args"].update(collective=1),
             "args.collective must be true")
    tampered(lambda r: r.update(exposed_collective_ms=-1.0), "negative")
    tampered(lambda r: r.update(exposed_collective_ms="nan"),
             "finite number")


def test_v9_perf_report_overlap_block_required_and_forbidden(tmp_path):
    """A REAL layerwise-overlap audit report validates with its v9
    overlap block; the checker rejects every mislabeling direction —
    config on without the block, block with config off, all-off block,
    and malformed fields."""
    mod = _checker()
    path = _write_perf_report(tmp_path, overlap_collectives="layerwise")
    rec = mod.validate_perf_report(path)
    assert rec["overlap"] == {"collectives": "layerwise",
                              "double_buffer": False}
    assert rec["meta"]["config"]["overlap_collectives"] == "layerwise"

    def tampered(mutate, msg):
        with open(path) as f:
            r = json.load(f)
        mutate(r)
        bad = os.path.join(str(tmp_path), "bad_report.json")
        with open(bad, "w") as f:
            json.dump(r, f)
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_perf_report(bad)

    # required direction: hiding mode on in config, block missing
    tampered(lambda r: r.pop("overlap"), "no 'overlap' block")
    # malformed fields
    tampered(lambda r: r["overlap"].update(collectives="bogus"),
             "'none' or 'layerwise'")
    tampered(lambda r: r["overlap"].update(double_buffer=1),
             "must be a bool")
    # an all-off block is a writer bug (the block exists to mark runs
    # whose wall-clock is overlap-dependent)
    tampered(lambda r: (r["overlap"].update(collectives="none"),
                        r["meta"]["config"].update(
                            overlap_collectives="none")),
             "every hiding mode off")
    # forbidden direction: block riding a config with hiding off
    tampered(lambda r: r["meta"]["config"].update(
        overlap_collectives="none"),
        "config has overlap_collectives='none'")


def test_v9_report_without_hiding_modes_has_no_overlap_block(tmp_path):
    """The default round's report stays block-free (v8 shape), and a v8
    artifact — config predating the overlap keys entirely — still
    validates."""
    mod = _checker()
    path = _write_perf_report(tmp_path)
    rec = mod.validate_perf_report(path)
    assert "overlap" not in rec
    assert rec["meta"]["config"]["overlap_collectives"] == "none"

    # a genuine v8 artifact: no overlap keys in config at all
    with open(path) as f:
        r = json.load(f)
    r["schema_version"] = 8
    r["meta"]["config"].pop("overlap_collectives")
    r["meta"]["config"].pop("async_double_buffer")
    old = os.path.join(str(tmp_path), "v8_report.json")
    with open(old, "w") as f:
        json.dump(r, f)
    mod.validate_perf_report(old)


def test_v10_clientstore_scalars_validate_and_reject(tmp_path):
    """The clientstore/ scalar prefix is in-schema through the REAL
    writer; value invariants (hit-rate fraction, integer eviction gauge,
    non-negative wall-clock) are enforced. The end-to-end form — these
    scalars riding a hosted run's drained metrics — is pinned by
    tests/test_clientstore.py."""
    mod = _checker()
    cfg = Config(mode="local_topk", error_type="local", local_momentum=0.9,
                 k=30, telemetry_level=1, num_workers=8, num_devices=8,
                 client_store="host", client_store_cache_rows=4)
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    for s in range(3):
        writer.scalar("train/loss", 1.0, s)
        writer.scalar("lr", 0.1, s)
        writer.scalar("clientstore/cache_hit_rate", 0.5, s)
        writer.scalar("clientstore/evictions", float(s), s)
        writer.scalar("clientstore/h2d_stage_ms", 0.3, s)
        writer.scalar("clientstore/writeback_ms", 0.0, s)
    writer.close()
    path = os.path.join(run_dir, "metrics.jsonl")
    assert mod.validate_metrics_jsonl(path) == 18
    header = open(path).readline()
    for bad_rec, msg in [
        ({"name": "clientstore/cache_hit_rate", "value": 1.5, "step": 0,
          "t": 1.0}, r"outside \[0, 1\]"),
        ({"name": "clientstore/cache_hit_rate", "value": -0.1, "step": 0,
          "t": 1.0}, r"outside \[0, 1\]"),
        ({"name": "clientstore/evictions", "value": 0.5, "step": 0,
          "t": 1.0}, "non-negative integer"),
        ({"name": "clientstore/evictions", "value": -1.0, "step": 0,
          "t": 1.0}, "non-negative integer"),
        ({"name": "clientstore/h2d_stage_ms", "value": -0.1, "step": 0,
          "t": 1.0}, "negative"),
        ({"name": "clientstore/writeback_ms", "value": -2.0, "step": 0,
          "t": 1.0}, "negative"),
        ({"name": "clientstore/cache_hit_rate", "value": True, "step": 0,
          "t": 1.0}, "neither a number"),
    ]:
        bad = tmp_path / "bad.jsonl"
        bad.write_text(header + json.dumps(bad_rec) + "\n")
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_metrics_jsonl(str(bad))


def test_v10_perf_report_rejects_hosted_exemption(tmp_path):
    """A sparse-aggregate report whose config hosts client state may not
    carry ANY sparse_agg_exemption (the [C, D] writeback gather does not
    exist in the hosted HLO); unknown exemption markers are rejected
    outright. The accepting side — a REAL hosted audit passing the strict
    bound — is pinned by tests/test_clientstore.py."""
    mod = _checker()
    path = _write_perf_report(tmp_path)
    rec = mod.validate_perf_report(path)
    assert rec["collectives"]["sparse_agg_exemption"] is None

    def tampered(mutate, msg):
        with open(path) as f:
            r = json.load(f)
        mutate(r)
        bad = os.path.join(str(tmp_path), "bad_perf.json")
        with open(bad, "w") as f:
            json.dump(r, f)
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_perf_report(bad)

    # recast as a sparse-aggregate report (generous bound: only the
    # exemption rules should fire)
    def sparse(r):
        r["aggregate"] = "sparse"
        r["collectives"]["sparse_agg_bound"] = 10 ** 9

    def unknown_marker(r):
        sparse(r)
        r["collectives"]["sparse_agg_exemption"] = "hand_wave"

    def host_with_exemption(r):
        sparse(r)
        r["meta"]["config"]["client_store"] = "host"
        r["collectives"]["sparse_agg_exemption"] = "client_state_writeback"

    tampered(unknown_marker, "unknown sparse_agg_exemption")
    tampered(host_with_exemption, "hosts client state")


# ---------------------------------------------------------------------------
# v11: trace/* scalars, span trace ids, and the run report
# ---------------------------------------------------------------------------

def test_v11_trace_scalars_validate_and_reject(tmp_path):
    """The trace/ critical-path prefix is in-schema through the REAL
    writer; the index/interval invariants are enforced on both scalar
    paths (metrics.jsonl and the flight recorder's metric blocks). The
    end-to-end form — these scalars riding a traced run's metrics — is
    pinned by tests/test_trace.py."""
    mod = _checker()
    cfg = Config(mode="uncompressed", telemetry_level=1, num_workers=8,
                 num_devices=8)
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    for s in range(3):
        writer.scalar("train/loss", 1.0, s)
        writer.scalar("lr", 0.1, s)
        # the lagged emission's zeros row, then a real attribution
        writer.scalar("trace/critical_stage", 6.0 if s < 2 else 3.0, s)
        writer.scalar("trace/collective_exclusive_ms",
                      0.0 if s < 2 else 1.25, s)
        writer.scalar("trace/idle_exclusive_ms", 0.0, s)
    writer.close()
    path = os.path.join(run_dir, "metrics.jsonl")
    assert mod.validate_metrics_jsonl(path) == 15
    header = open(path).readline()
    for bad_rec, msg in [
        ({"name": "trace/idle_exclusive_ms", "value": -0.5, "step": 0,
          "t": 1.0}, "negative"),
        ({"name": "trace/dispatch_exclusive_ms", "value": -2.0, "step": 0,
          "t": 1.0}, "negative"),
        ({"name": "trace/critical_stage", "value": 3.5, "step": 0,
          "t": 1.0}, "integer index"),
        ({"name": "trace/critical_stage", "value": -1.0, "step": 0,
          "t": 1.0}, "integer index"),
        ({"name": "trace/critical_stage", "value": 7.0, "step": 0,
          "t": 1.0}, "integer index"),
        ({"name": "trace/critical_stage", "value": "nan", "step": 0,
          "t": 1.0}, "finite number"),
    ]:
        bad = tmp_path / "bad.jsonl"
        bad.write_text(header + json.dumps(bad_rec) + "\n")
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_metrics_jsonl(str(bad))

    # same invariants hold on the flight recorder's metric blocks
    flight = FlightRecorder(cfg, logdir=str(tmp_path))
    for s in range(3):
        flight.record(s, 0.1, {"loss": 1.0, "trace/critical_stage": 6.0,
                               "trace/idle_exclusive_ms": 0.25})
    fpath = flight.dump(2, reason="test dump", first_bad_step=2)
    mod.validate_flight(fpath)

    def tampered(mutate, msg):
        with open(fpath) as f:
            r = json.load(f)
        mutate(r)
        bad = os.path.join(str(tmp_path), "bad_flight.json")
        with open(bad, "w") as f:
            json.dump(r, f)
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_flight(bad)

    tampered(lambda r: r["records"][0]["scalars"].update(
        {"trace/idle_exclusive_ms": -1.0}), "negative")
    tampered(lambda r: r["records"][0]["scalars"].update(
        {"trace/critical_stage": 2.5}), "integer index")


def test_v11_spans_trace_id_rules(tmp_path):
    """Span trace correlation through the REAL recorder: a cohort span
    with a round parent validates; an empty trace_id, a bare parent
    (no trace_id), and a self-parented span are rejected."""
    from commefficient_tpu.telemetry.spans import PhaseSpans

    mod = _checker()
    spans = PhaseSpans(str(tmp_path))
    spans.step(2)
    with spans.span("round_dispatch", trace_id="r2"):
        pass
    with spans.span("async_launch", step=2, trace_id="c1", parent="r2"):
        pass
    with spans.span("metric_drain"):  # correlation is OPTIONAL per span
        pass
    path = spans.close()
    rec = mod.validate_spans(path)
    evs = [e for e in rec["traceEvents"] if e["ph"] == "X"]
    assert {e["args"].get("trace_id") for e in evs} == {"r2", "c1", None}
    launch = next(e for e in evs if e["name"] == "async_launch")
    assert launch["args"]["parent"] == "r2"

    def tampered(mutate, msg):
        with open(path) as f:
            r = json.load(f)
        mutate(r)
        bad = os.path.join(str(tmp_path), "bad_spans.json")
        with open(bad, "w") as f:
            json.dump(r, f)
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_spans(bad)

    def x_events(r):
        return [e for e in r["traceEvents"] if e["ph"] == "X"]

    tampered(lambda r: x_events(r)[0]["args"].update(trace_id=""),
             "non-empty string")
    tampered(lambda r: x_events(r)[2]["args"].update(parent="r9"),
             "without args.trace_id")
    tampered(lambda r: x_events(r)[1]["args"].update(parent="c1"),
             "own causal parent")


def test_v11_run_report_validates_and_rejects(tmp_path):
    """The run report through the REAL builder (telemetry/trace.py) over
    a real spans dump, then the attribution invariants: overlapping
    stage intervals (exclusive sums past the wall), negative stage
    times, a broken binding-stage count, and off-taxonomy stages are
    all caught — the checker cannot rot into a vacuous pass."""
    from commefficient_tpu.telemetry.spans import PhaseSpans
    from commefficient_tpu.telemetry.trace import write_run_report

    mod = _checker()
    spans = PhaseSpans(str(tmp_path))
    for s in range(2):
        spans.step(s)
        with spans.span("device_put", step=s, trace_id=f"r{s}"):
            pass
        with spans.span("round_dispatch", step=s, collective=True,
                        trace_id=f"r{s}"):
            pass
    spans.close()
    path = write_run_report(str(tmp_path), generated_by="schema test")
    rec = mod.validate_run_report(path)
    assert rec["rounds_analyzed"] == 2
    # the run-dir walk picks the report up alongside the spans dump
    walk = mod.validate_run_dir(str(tmp_path))
    assert any(p.endswith("run_report.json") for p in walk)

    def tampered(mutate, msg):
        with open(path) as f:
            r = json.load(f)
        mutate(r)
        bad = os.path.join(str(tmp_path), "bad_report.json")
        with open(bad, "w") as f:
            json.dump(r, f)
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_run_report(bad)

    def overlap(r):
        # charge the same microseconds twice: the exclusive sums now
        # exceed the round's wall-clock
        r["rounds"][0]["stages_ms"]["data"] += \
            r["rounds"][0]["wall_ms"] + 1.0

    tampered(overlap, "stages overlap")
    tampered(lambda r: r["rounds"][0]["stages_ms"].update(h2d=-0.25),
             "negative")
    tampered(lambda r: r["rounds"][0].update(critical_stage="turbo"),
             "outside the stage taxonomy")
    tampered(lambda r: r.update(critical_stage="turbo"),
             "outside the stage taxonomy")
    tampered(lambda r: r["critical_counts"].update(idle=5),
             "critical_counts sum")
    tampered(lambda r: r["critical_counts"].pop("idle"),
             "stage taxonomy")
    tampered(lambda r: r["stages"]["idle"].update(fraction=0.9),
             "fractions sum")
    tampered(lambda r: r["stages"]["idle"].update(p50_ms=-1.0),
             ">= 0")
    tampered(lambda r: r.update(rounds=r["rounds"][:1]),
             "per-round entries")
    tampered(lambda r: r.update(kind="bench"), "kind must be")


# ---------------------------------------------------------------------------
# v12: multihost/* scalars and the perf-report multihost block
# ---------------------------------------------------------------------------

def test_v12_multihost_scalars_validate_and_reject(tmp_path):
    """The multihost/ topology prefix is in-schema through the REAL
    writer (the end-to-end form — these scalars riding a num_hosts > 1
    session's rounds — is pinned by tests/test_multihost.py); the
    value invariants reject every tampering direction on both scalar
    paths."""
    mod = _checker()
    cfg = Config(mode="uncompressed", telemetry_level=1, num_workers=8,
                 num_devices=8, num_hosts=2)
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    for s in range(3):
        writer.scalar("train/loss", 1.0, s)
        writer.scalar("lr", 0.1, s)
        # 1 process = the mesh-faked twin; bytes/exposure are gauges
        writer.scalar("multihost/num_processes", 1.0, s)
        writer.scalar("multihost/host_id", 0.0, s)
        writer.scalar("multihost/cross_host_bytes", 4096.0 * s, s)
        writer.scalar("multihost/dcn_exposed_ms", 0.5 * s, s)
    writer.close()
    path = os.path.join(run_dir, "metrics.jsonl")
    assert mod.validate_metrics_jsonl(path) == 18
    header = open(path).readline()
    for bad_rec, msg in [
        ({"name": "multihost/num_processes", "value": 0.0, "step": 0,
          "t": 1.0}, "positive"),
        ({"name": "multihost/num_processes", "value": 1.5, "step": 0,
          "t": 1.0}, "positive"),
        ({"name": "multihost/host_id", "value": -1.0, "step": 0,
          "t": 1.0}, "non-negative"),
        ({"name": "multihost/host_id", "value": 0.5, "step": 0,
          "t": 1.0}, "non-negative"),
        ({"name": "multihost/cross_host_bytes", "value": -4096.0,
          "step": 0, "t": 1.0}, "negative"),
        ({"name": "multihost/dcn_exposed_ms", "value": -0.5, "step": 0,
          "t": 1.0}, "negative"),
        ({"name": "multihost/num_processes", "value": "nan", "step": 0,
          "t": 1.0}, "finite number"),
    ]:
        bad = tmp_path / "bad.jsonl"
        bad.write_text(header + json.dumps(bad_rec) + "\n")
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_metrics_jsonl(str(bad))

    # same invariants hold on the flight recorder's metric blocks
    flight = FlightRecorder(cfg, logdir=str(tmp_path))
    for s in range(3):
        flight.record(s, 0.1, {"loss": 1.0, "multihost/num_processes": 1.0,
                               "multihost/cross_host_bytes": 4096.0})
    fpath = flight.dump(2, reason="test dump", first_bad_step=2)
    mod.validate_flight(fpath)

    def tampered(mutate, msg):
        with open(fpath) as f:
            r = json.load(f)
        mutate(r)
        bad = os.path.join(str(tmp_path), "bad_flight.json")
        with open(bad, "w") as f:
            json.dump(r, f)
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_flight(bad)

    tampered(lambda r: r["records"][0]["scalars"].update(
        {"multihost/num_processes": 0.0}), "positive")
    tampered(lambda r: r["records"][0]["scalars"].update(
        {"multihost/cross_host_bytes": -1.0}), "negative")


def test_v12_perf_report_multihost_block_required_and_forbidden(tmp_path):
    """A REAL mesh-faked 2-host audit report carries the topology block
    and validates; the checker rejects every mislabeling direction —
    block removed from a multi-host report, single-host geometry inside
    the block, host_id outside the pod, and the block riding a report
    whose config declares no host axis."""
    mod = _checker()
    path = _write_perf_report(tmp_path, num_hosts=2)
    rec = mod.validate_perf_report(path)
    assert rec["multihost"] == {"num_hosts": 2, "num_processes": 1,
                                "host_id": 0}

    def tampered(mutate, msg):
        with open(path) as f:
            r = json.load(f)
        mutate(r)
        bad = os.path.join(str(tmp_path), "bad_report.json")
        with open(bad, "w") as f:
            json.dump(r, f)
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_perf_report(bad)

    tampered(lambda r: r.pop("multihost"), "no 'multihost' block")
    tampered(lambda r: r["multihost"].update(num_hosts=1),
             "integer >= 2")
    tampered(lambda r: r["multihost"].update(num_hosts=2.5),
             "integer >= 2")
    tampered(lambda r: r["multihost"].update(num_processes=0),
             "integer >= 1")
    tampered(lambda r: r["multihost"].update(host_id=1),
             "outside")
    tampered(lambda r: r["multihost"].update(host_id=-1),
             "outside")
    # forbidden direction: the block riding a single-host report
    tampered(lambda r: r["meta"]["config"].update(num_hosts=1),
             "mislabeled producer")


# ---------------------------------------------------------------------------
# v13: fleet/* + control/async_* (elastic fleet / staleness_aware)
# ---------------------------------------------------------------------------

def test_v13_fleet_scalars_validate_and_reject(tmp_path):
    """The fleet/ prefix is in-schema through the REAL writer (the
    end-to-end form — these scalars riding a real elastic run — is
    pinned by tests/test_fleet.py); the positive-width, counted-event
    and no-resize-from-the-future invariants reject tampering."""
    mod = _checker()
    cfg = Config(mode="uncompressed", telemetry_level=1, num_workers=8,
                 num_devices=4, chaos="resize@4:rounds=1-2")
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    for s, (w, n, last) in enumerate([(8, 0, -1), (4, 1, 1), (4, 1, 1)]):
        writer.scalar("train/loss", 1.0, s)
        writer.scalar("lr", 0.1, s)
        writer.scalar("fleet/width", float(w), s)
        writer.scalar("fleet/resizes", float(n), s)
        writer.scalar("fleet/last_resize_round", float(last), s)
        writer.scalar("fleet/shrink_recoveries", 0.0, s)
    writer.close()
    path = os.path.join(run_dir, "metrics.jsonl")
    assert mod.validate_metrics_jsonl(path) == 18
    header = open(path).readline()
    for bad_rec, msg in [
        ({"name": "fleet/width", "value": 0.0, "step": 0, "t": 1.0},
         "positive integer"),
        ({"name": "fleet/width", "value": 4.5, "step": 0, "t": 1.0},
         "positive integer"),
        ({"name": "fleet/resizes", "value": -1.0, "step": 0, "t": 1.0},
         "non-negative integer"),
        ({"name": "fleet/shrink_recoveries", "value": 0.5, "step": 0,
          "t": 1.0}, "non-negative integer"),
        ({"name": "fleet/last_resize_round", "value": -2.0, "step": 0,
          "t": 1.0}, ">= -1"),
        # a resize cannot postdate the round reporting it
        ({"name": "fleet/last_resize_round", "value": 5.0, "step": 2,
          "t": 1.0}, "postdates"),
        ({"name": "fleet/width", "value": "nan", "step": 0, "t": 1.0},
         "finite number"),
    ]:
        bad = tmp_path / "bad.jsonl"
        bad.write_text(header + json.dumps(bad_rec) + "\n")
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_metrics_jsonl(str(bad))


def test_v13_control_async_scalars_validate_and_reject(tmp_path):
    mod = _checker()
    cfg = Config(mode="uncompressed", telemetry_level=1)
    run_dir = str(tmp_path / "run")
    writer = MetricsWriter(run_dir, cfg=cfg)
    for s in range(2):
        writer.scalar("train/loss", 1.0, s)
        writer.scalar("lr", 0.1, s)
        writer.scalar("control/async_k", 4.0, s)
        writer.scalar("control/async_c", float(2 - s), s)
        writer.scalar("control/retunes", float(s), s)
    writer.close()
    path = os.path.join(run_dir, "metrics.jsonl")
    with pytest.raises(mod.SchemaError, match="K >= 1, C >= 1"):
        # the controller clamps C >= 1: the s=1 row above wrote 1.0, so
        # tamper a 0 to prove the rule bites
        bad = tmp_path / "bad.jsonl"
        bad.write_text(open(path).readline() + json.dumps(
            {"name": "control/async_c", "value": 0.0, "step": 0,
             "t": 1.0}) + "\n")
        mod.validate_metrics_jsonl(str(bad))
    assert mod.validate_metrics_jsonl(path) == 10
    for bad_rec, msg in [
        ({"name": "control/async_k", "value": 0.0, "step": 0, "t": 1.0},
         "K >= 1"),
        ({"name": "control/async_k", "value": 2.5, "step": 0, "t": 1.0},
         "positive integer"),
        ({"name": "control/retunes", "value": -1.0, "step": 0, "t": 1.0},
         "non-negative"),
    ]:
        bad = tmp_path / "bad.jsonl"
        bad.write_text(open(path).readline() + json.dumps(bad_rec) + "\n")
        with pytest.raises(mod.SchemaError, match=msg):
            mod.validate_metrics_jsonl(str(bad))


def test_v13_flight_fleet_resizes_monotone(tmp_path):
    """Flight-ring rule: fleet/resizes is a cumulative transition count,
    so within one dump's step-ordered records it may never fall — a fall
    means rolled-back records were spliced into the ring."""
    from commefficient_tpu.telemetry import FlightRecorder

    mod = _checker()
    cfg = Config(mode="uncompressed", telemetry_level=1, num_workers=8,
                 num_devices=4, chaos="resize@4:rounds=1-2")
    good = FlightRecorder(cfg, logdir=str(tmp_path))
    for s, n in enumerate([0.0, 1.0, 1.0, 2.0]):
        good.record(s, 0.1, {"loss": 1.0, "fleet/width": 8.0,
                             "fleet/resizes": n,
                             "fleet/last_resize_round": -1.0})
    path = good.dump(3, reason="ok", first_bad_step=3)
    mod.validate_flight(path)
    bad = FlightRecorder(cfg, logdir=str(tmp_path / "bad"))
    for s, n in enumerate([0.0, 1.0, 0.0]):
        bad.record(s, 0.1, {"loss": 1.0, "fleet/width": 8.0,
                            "fleet/resizes": n,
                            "fleet/last_resize_round": -1.0})
    path = bad.dump(2, reason="bad", first_bad_step=2)
    with pytest.raises(mod.SchemaError, match="fell from 1"):
        mod.validate_flight(path)
