"""Property tests for the Count Sketch library.

Ports the contract of the reference's csvec test suite
(``nikitaivkin/csh::test_csvec.py``, per SURVEY.md §4): heavy-hitter
recovery, linearity, l2 estimation — plus hash-quality and determinism checks
specific to our stateless hashing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.ops import (
    CountSketch,
    sketch_vec,
    unsketch,
    estimate_all,
    l2_estimate,
)
from commefficient_tpu.ops.countsketch import estimate_at, sketch_add_vec

D, C, R = 10_000, 2_000, 5


@pytest.fixture(scope="module")
def spec():
    return CountSketch(d=D, c=C, r=R, num_blocks=4, seed=7)


def planted_vector(d, k, rng, heavy=100.0, noise=1.0):
    """Dense vector with k heavy coordinates over light gaussian noise."""
    v = rng.normal(0, noise, size=d).astype(np.float32)
    idx = rng.choice(d, size=k, replace=False)
    signs = rng.choice([-1.0, 1.0], size=k)
    v[idx] += heavy * signs
    return jnp.asarray(v), np.asarray(idx)


def test_recovers_planted_heavy_hitters(spec):
    rng = np.random.default_rng(0)
    v, hh = planted_vector(D, 20, rng)
    table = sketch_vec(spec, v)
    rec = unsketch(spec, table, k=20)
    rec_idx = set(np.nonzero(np.asarray(rec))[0].tolist())
    assert set(hh.tolist()) <= rec_idx
    # recovered values close to true values on the heavy coords
    np.testing.assert_allclose(
        np.asarray(rec)[hh], np.asarray(v)[hh], rtol=0.15, atol=2.0
    )


def test_linearity(spec):
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.normal(size=D).astype(np.float32))
    b = jnp.asarray(rng.normal(size=D).astype(np.float32))
    t_sum = sketch_vec(spec, a + b)
    t_parts = sketch_vec(spec, a) + sketch_vec(spec, b)
    np.testing.assert_allclose(np.asarray(t_sum), np.asarray(t_parts), rtol=1e-4, atol=1e-3)


def test_sketch_add_vec_matches_fresh(spec):
    rng = np.random.default_rng(5)
    a = jnp.asarray(rng.normal(size=D).astype(np.float32))
    b = jnp.asarray(rng.normal(size=D).astype(np.float32))
    t = sketch_add_vec(spec, sketch_vec(spec, a), b)
    np.testing.assert_allclose(
        np.asarray(t), np.asarray(sketch_vec(spec, a + b)), rtol=1e-4, atol=1e-3
    )


def test_l2_estimate(spec):
    rng = np.random.default_rng(2)
    v = jnp.asarray(rng.normal(size=D).astype(np.float32))
    est = float(l2_estimate(spec, sketch_vec(spec, v)))
    true = float(jnp.linalg.norm(v))
    assert abs(est - true) / true < 0.25


def test_estimate_all_matches_estimate_at(spec):
    rng = np.random.default_rng(3)
    v = jnp.asarray(rng.normal(size=D).astype(np.float32))
    table = sketch_vec(spec, v)
    full = estimate_all(spec, table)
    idx = jnp.asarray(rng.choice(D, size=100, replace=False).astype(np.int32))
    np.testing.assert_allclose(
        np.asarray(full)[np.asarray(idx)],
        np.asarray(estimate_at(spec, table, idx)),
        rtol=1e-5,
    )


def test_num_blocks_invariance():
    """Blockwise estimation is a memory knob, not a semantics knob."""
    rng = np.random.default_rng(4)
    v = jnp.asarray(rng.normal(size=D).astype(np.float32))
    tables = {}
    for nb in (1, 4, 7):
        sp = CountSketch(d=D, c=C, r=R, num_blocks=nb, seed=7)
        tables[nb] = np.asarray(estimate_all(sp, sketch_vec(sp, v)))
    np.testing.assert_allclose(tables[1], tables[4], rtol=1e-5)
    np.testing.assert_allclose(tables[1], tables[7], rtol=1e-5)


def test_determinism_across_instances(spec):
    """Same seed => same hashes => same tables (the property that lets server
    and workers agree without communicating hash state)."""
    v = jnp.ones(D, dtype=jnp.float32)
    spec2 = CountSketch(d=D, c=C, r=R, num_blocks=4, seed=7)
    np.testing.assert_array_equal(
        np.asarray(sketch_vec(spec, v)), np.asarray(sketch_vec(spec2, v))
    )
    spec3 = CountSketch(d=D, c=C, r=R, num_blocks=4, seed=8)
    assert not np.array_equal(
        np.asarray(sketch_vec(spec, v)), np.asarray(sketch_vec(spec3, v))
    )


def test_hash_quality(spec):
    """Slots roughly uniform; signs roughly balanced; rows decorrelated."""
    all_slots = []
    for row in range(R):
        slots = np.asarray(spec._row_slots(row)).ravel()
        counts = np.bincount(slots, minlength=spec.s)
        assert counts.max() < 3 * (spec.d_padded / spec.s)
        signs = np.asarray(spec._row_signs(row))
        assert abs(signs.mean()) < 0.05
        all_slots.append(slots)
    # slot agreement between rows ~ 1/s (independent hashing per row)
    for i in range(R):
        for j in range(i + 1, R):
            agree = np.mean(all_slots[i] == all_slots[j])
            assert abs(agree - 1.0 / spec.s) < 0.02


def test_rolls_differ_across_rows(spec):
    """Per-row rolls stagger chunk boundaries, so near pairs don't share a
    chunk in every row (the property that lets the median reject same-chunk
    collision noise)."""
    rolls = {spec._roll(r) for r in range(R)}
    assert len(rolls) == R


def test_recovers_clustered_heavy_hitters(spec):
    """Adversarial for the blocked layout: heavy hitters packed into ONE
    contiguous chunk region must still be recovered (within-chunk capacity
    s >> 20 plus cross-row rolls)."""
    rng = np.random.default_rng(9)
    v = rng.normal(0, 1.0, size=D).astype(np.float32)
    start = 3 * spec.chunk_m + 17
    hh = np.arange(start, start + 20)
    v[hh] += 100.0 * rng.choice([-1.0, 1.0], size=20)
    table = sketch_vec(spec, jnp.asarray(v))
    rec = unsketch(spec, table, k=20)
    rec_idx = set(np.nonzero(np.asarray(rec))[0].tolist())
    assert set(hh.tolist()) <= rec_idx


def test_jit_and_grad_safety(spec):
    """sketch/unsketch compile under jit and work on traced values."""
    v = jnp.ones(D, dtype=jnp.float32)

    @jax.jit
    def roundtrip(v):
        return unsketch(spec, sketch_vec(spec, v), k=10)

    out = roundtrip(v)
    assert out.shape == (D,)
    assert int(jnp.sum(out != 0)) <= 10
