"""Property tests for the Count Sketch library.

Ports the contract of the reference's csvec test suite
(``nikitaivkin/csh::test_csvec.py``, per SURVEY.md §4): heavy-hitter
recovery, linearity, l2 estimation — plus hash-quality and determinism checks
specific to our stateless hashing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.ops import (
    CountSketch,
    sketch_vec,
    unsketch,
    estimate_all,
    l2_estimate,
)
from commefficient_tpu.ops.countsketch import estimate_at, sketch_add_vec

D, C, R = 10_000, 2_000, 5


@pytest.fixture(scope="module")
def spec():
    return CountSketch(d=D, c=C, r=R, num_blocks=4, seed=7)


def planted_vector(d, k, rng, heavy=100.0, noise=1.0):
    """Dense vector with k heavy coordinates over light gaussian noise."""
    v = rng.normal(0, noise, size=d).astype(np.float32)
    idx = rng.choice(d, size=k, replace=False)
    signs = rng.choice([-1.0, 1.0], size=k)
    v[idx] += heavy * signs
    return jnp.asarray(v), np.asarray(idx)


def test_recovers_planted_heavy_hitters(spec):
    rng = np.random.default_rng(0)
    v, hh = planted_vector(D, 20, rng)
    table = sketch_vec(spec, v)
    rec = unsketch(spec, table, k=20)
    rec_idx = set(np.nonzero(np.asarray(rec))[0].tolist())
    assert set(hh.tolist()) <= rec_idx
    # recovered values close to true values on the heavy coords
    np.testing.assert_allclose(
        np.asarray(rec)[hh], np.asarray(v)[hh], rtol=0.15, atol=2.0
    )


def test_linearity(spec):
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.normal(size=D).astype(np.float32))
    b = jnp.asarray(rng.normal(size=D).astype(np.float32))
    t_sum = sketch_vec(spec, a + b)
    t_parts = sketch_vec(spec, a) + sketch_vec(spec, b)
    np.testing.assert_allclose(np.asarray(t_sum), np.asarray(t_parts), rtol=1e-4, atol=1e-3)


def test_sketch_add_vec_matches_fresh(spec):
    rng = np.random.default_rng(5)
    a = jnp.asarray(rng.normal(size=D).astype(np.float32))
    b = jnp.asarray(rng.normal(size=D).astype(np.float32))
    t = sketch_add_vec(spec, sketch_vec(spec, a), b)
    np.testing.assert_allclose(
        np.asarray(t), np.asarray(sketch_vec(spec, a + b)), rtol=1e-4, atol=1e-3
    )


def test_l2_estimate(spec):
    rng = np.random.default_rng(2)
    v = jnp.asarray(rng.normal(size=D).astype(np.float32))
    est = float(l2_estimate(spec, sketch_vec(spec, v)))
    true = float(jnp.linalg.norm(v))
    assert abs(est - true) / true < 0.25


def test_estimate_all_matches_estimate_at(spec):
    rng = np.random.default_rng(3)
    v = jnp.asarray(rng.normal(size=D).astype(np.float32))
    table = sketch_vec(spec, v)
    full = estimate_all(spec, table)
    idx = jnp.asarray(rng.choice(D, size=100, replace=False).astype(np.int32))
    np.testing.assert_allclose(
        np.asarray(full)[np.asarray(idx)],
        np.asarray(estimate_at(spec, table, idx)),
        rtol=1e-5,
    )


def test_num_blocks_invariance():
    """Blockwise estimation is a memory knob, not a semantics knob."""
    rng = np.random.default_rng(4)
    v = jnp.asarray(rng.normal(size=D).astype(np.float32))
    tables = {}
    for nb in (1, 4, 7):
        sp = CountSketch(d=D, c=C, r=R, num_blocks=nb, seed=7)
        tables[nb] = np.asarray(estimate_all(sp, sketch_vec(sp, v)))
    np.testing.assert_allclose(tables[1], tables[4], rtol=1e-5)
    np.testing.assert_allclose(tables[1], tables[7], rtol=1e-5)


def test_determinism_across_instances(spec):
    """Same seed => same hashes => same tables (the property that lets server
    and workers agree without communicating hash state)."""
    v = jnp.ones(D, dtype=jnp.float32)
    spec2 = CountSketch(d=D, c=C, r=R, num_blocks=4, seed=7)
    np.testing.assert_array_equal(
        np.asarray(sketch_vec(spec, v)), np.asarray(sketch_vec(spec2, v))
    )
    spec3 = CountSketch(d=D, c=C, r=R, num_blocks=4, seed=8)
    assert not np.array_equal(
        np.asarray(sketch_vec(spec, v)), np.asarray(sketch_vec(spec3, v))
    )


def test_hash_quality(spec):
    """Buckets roughly uniform; signs roughly balanced; rows decorrelated."""
    idx = jnp.arange(D, dtype=jnp.uint32)
    keys = spec._row_keys()
    all_buckets = []
    for rk in np.asarray(keys):
        b, s = spec.buckets_signs(idx, jnp.uint32(rk))
        b, s = np.asarray(b), np.asarray(s)
        counts = np.bincount(b, minlength=C)
        assert counts.max() < 5 * (D / C)  # no catastrophically hot bucket
        assert abs(s.mean()) < 0.05  # balanced signs
        all_buckets.append(b)
    for i in range(R):
        for j in range(i + 1, R):
            assert np.mean(all_buckets[i] == all_buckets[j]) < 5.0 / C * 3 + 0.01


def test_jit_and_grad_safety(spec):
    """sketch/unsketch compile under jit and work on traced values."""
    v = jnp.ones(D, dtype=jnp.float32)

    @jax.jit
    def roundtrip(v):
        return unsketch(spec, sketch_vec(spec, v), k=10)

    out = roundtrip(v)
    assert out.shape == (D,)
    assert int(jnp.sum(out != 0)) <= 10
