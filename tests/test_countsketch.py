"""Property tests for the Count Sketch library.

Ports the contract of the reference's csvec test suite
(``nikitaivkin/csh::test_csvec.py``, per SURVEY.md §4): heavy-hitter
recovery, linearity, l2 estimation — plus hash-quality and determinism checks
specific to our stateless hashing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.ops import (
    CountSketch,
    sketch_vec,
    sketch_sparse,
    unsketch,
    unsketch_sparse,
    estimate_all,
    l2_estimate,
)
from commefficient_tpu.ops.countsketch import estimate_at, sketch_add_vec

D, C, R = 10_000, 2_000, 5


@pytest.fixture(scope="module")
def spec():
    return CountSketch(d=D, c=C, r=R, num_blocks=4, seed=7)


def planted_vector(d, k, rng, heavy=100.0, noise=1.0):
    """Dense vector with k heavy coordinates over light gaussian noise."""
    v = rng.normal(0, noise, size=d).astype(np.float32)
    idx = rng.choice(d, size=k, replace=False)
    signs = rng.choice([-1.0, 1.0], size=k)
    v[idx] += heavy * signs
    return jnp.asarray(v), np.asarray(idx)


def test_recovers_planted_heavy_hitters(spec):
    rng = np.random.default_rng(0)
    v, hh = planted_vector(D, 20, rng)
    table = sketch_vec(spec, v)
    rec = unsketch(spec, table, k=20)
    rec_idx = set(np.nonzero(np.asarray(rec))[0].tolist())
    assert set(hh.tolist()) <= rec_idx
    # recovered values close to true values on the heavy coords
    np.testing.assert_allclose(
        np.asarray(rec)[hh], np.asarray(v)[hh], rtol=0.15, atol=2.0
    )


def test_linearity(spec):
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.normal(size=D).astype(np.float32))
    b = jnp.asarray(rng.normal(size=D).astype(np.float32))
    t_sum = sketch_vec(spec, a + b)
    t_parts = sketch_vec(spec, a) + sketch_vec(spec, b)
    np.testing.assert_allclose(np.asarray(t_sum), np.asarray(t_parts), rtol=1e-4, atol=1e-3)


def test_sketch_add_vec_matches_fresh(spec):
    rng = np.random.default_rng(5)
    a = jnp.asarray(rng.normal(size=D).astype(np.float32))
    b = jnp.asarray(rng.normal(size=D).astype(np.float32))
    t = sketch_add_vec(spec, sketch_vec(spec, a), b)
    np.testing.assert_allclose(
        np.asarray(t), np.asarray(sketch_vec(spec, a + b)), rtol=1e-4, atol=1e-3
    )


def test_l2_estimate(spec):
    rng = np.random.default_rng(2)
    v = jnp.asarray(rng.normal(size=D).astype(np.float32))
    est = float(l2_estimate(spec, sketch_vec(spec, v)))
    true = float(jnp.linalg.norm(v))
    assert abs(est - true) / true < 0.25


def test_estimate_all_matches_estimate_at(spec):
    rng = np.random.default_rng(3)
    v = jnp.asarray(rng.normal(size=D).astype(np.float32))
    table = sketch_vec(spec, v)
    full = estimate_all(spec, table)
    idx = jnp.asarray(rng.choice(D, size=100, replace=False).astype(np.int32))
    np.testing.assert_allclose(
        np.asarray(full)[np.asarray(idx)],
        np.asarray(estimate_at(spec, table, idx)),
        rtol=1e-5,
    )


def test_num_blocks_invariance():
    """Blockwise estimation is a memory knob, not a semantics knob."""
    rng = np.random.default_rng(4)
    v = jnp.asarray(rng.normal(size=D).astype(np.float32))
    tables = {}
    for nb in (1, 4, 7):
        sp = CountSketch(d=D, c=C, r=R, num_blocks=nb, seed=7)
        tables[nb] = np.asarray(estimate_all(sp, sketch_vec(sp, v)))
    np.testing.assert_allclose(tables[1], tables[4], rtol=1e-5)
    np.testing.assert_allclose(tables[1], tables[7], rtol=1e-5)


def test_determinism_across_instances(spec):
    """Same seed => same hashes => same tables (the property that lets server
    and workers agree without communicating hash state)."""
    v = jnp.ones(D, dtype=jnp.float32)
    spec2 = CountSketch(d=D, c=C, r=R, num_blocks=4, seed=7)
    np.testing.assert_array_equal(
        np.asarray(sketch_vec(spec, v)), np.asarray(sketch_vec(spec2, v))
    )
    spec3 = CountSketch(d=D, c=C, r=R, num_blocks=4, seed=8)
    assert not np.array_equal(
        np.asarray(sketch_vec(spec, v)), np.asarray(sketch_vec(spec3, v))
    )


def test_hash_quality(spec):
    """Slots roughly uniform; signs roughly balanced; rows decorrelated.
    s varies per row (per-row padding), so every check uses s_row."""
    all_slots = []
    for row in range(R):
        v_r = spec.V_row(row)  # v5: offsets hash into the chunk's V-window
        slots = np.asarray(spec._offset_slots(row))  # [m] per-offset buckets
        assert slots.max() < v_r
        counts = np.bincount(slots, minlength=v_r)
        # m balls into V bins: max load within a small factor of the mean
        mean_load = spec.chunk_m / v_r
        assert counts.max() <= 4 * max(1.0, mean_load) + 3
        # min-load / balance bound adapted to the banded geometry (ADVICE
        # r2: the V-window move dropped the old 'no starved buckets'
        # assertion). At this m/V the Poisson-expected empty fraction is
        # e^-mean_load — a degenerate _offset_slots (e.g. collapsing to a
        # sub-window) at least doubles it. 6-sigma binomial slack.
        empty_frac = np.mean(counts == 0)
        expect_empty = np.exp(-mean_load)
        sigma = np.sqrt(max(expect_empty * (1 - expect_empty), 1e-12) / v_r)
        assert empty_frac <= expect_empty + 6 * sigma + 1e-3, (
            row, empty_frac, expect_empty)
        signs = np.asarray(spec._row_signs(row))
        assert abs(signs.mean()) < 0.05
        all_slots.append(slots)
    # slot agreement between rows ~ 1/max(V_i, V_j), with binomial slack
    for i in range(R):
        for j in range(i + 1, R):
            agree = np.mean(all_slots[i] == all_slots[j])
            expect = 1.0 / max(spec.V_row(i), spec.V_row(j))
            sigma = (expect / spec.chunk_m) ** 0.5
            assert abs(agree - expect) < 6 * sigma + 1e-3, (i, j, agree, expect)


def test_riffle_factors_differ_across_rows(spec):
    """Each row riffles with a distinct prime factor, so co-chunk partner
    sets are disjoint across rows (the property that keeps the median
    sound — see the v2 postmortem in the module docstring)."""
    factors = [spec._factor(r) for r in range(R)]
    assert len(set(factors)) == R


def test_repeated_partner_collisions_at_classic_rate(spec):
    """v2 POSTMORTEM REGRESSION: the number of coordinate PAIRS that share
    a bucket in >= 2 of the r rows must be near the classic-sketch rate
    (~ D^2 * C(r,2) / (2 c^2)), not the ~(c/s)x inflated rate of the v2
    roll/stride layout. That inflation is what made FetchSGD error
    feedback diverge."""
    from commefficient_tpu.ops.countsketch import _row_cols_signs

    idx = jnp.arange(D)
    cols = np.stack(
        [np.asarray(_row_cols_signs(spec, idx, r)[0]) for r in range(R)]
    )  # [R, D] bucket column of every coordinate per row
    c = spec.c_actual
    pairs_2row = 0
    for i in range(R):
        for j in range(i + 1, R):
            key = cols[i].astype(np.int64) * c + cols[j]
            counts = np.bincount(key - key.min())
            pairs_2row += int((counts * (counts - 1) // 2).sum())
    classic_expect = D * D * (R * (R - 1) / 2) / (2.0 * c * c)
    # v2 measured ~100-200x classic here; allow generous stochastic slack
    assert pairs_2row <= 8 * classic_expect + 20, (
        f"{pairs_2row} repeated-partner pairs vs classic ~{classic_expect:.0f}"
    )


def test_recovers_clustered_heavy_hitters():
    """Adversarial for the blocked layout: heavy hitters packed into ONE
    contiguous run must be recovered without phantoms. Uses a spec in the
    riffle ladder's STRONG regime (nc >= m — the production-scale shape;
    here via an explicit small m), where any coordinate pair co-chunks in
    at most 2 of 5 rows and the median is clean. The adaptive-m default at
    toy d sits in the documented weak regime (see _riffle_factors)."""
    cspec = CountSketch(d=D, c=C, r=R, seed=7, m=64)
    assert cspec._nc_row(0) >= cspec.chunk_m  # strong regime
    rng = np.random.default_rng(9)
    v = rng.normal(0, 1.0, size=D).astype(np.float32)
    start = 3 * cspec.chunk_m + 17
    hh = np.arange(start, start + 20)
    v[hh] += 100.0 * rng.choice([-1.0, 1.0], size=20)
    table = sketch_vec(cspec, jnp.asarray(v))
    rec = unsketch(cspec, table, k=20)
    rec_idx = set(np.nonzero(np.asarray(rec))[0].tolist())
    assert set(hh.tolist()) <= rec_idx


def test_sketch_sparse_matches_dense_sketch(spec):
    """sketch_sparse of (idx, vals) == sketch_vec of the dense materialization
    — the server's fast path for subtracting the k-sparse extracted update."""
    rng = np.random.default_rng(11)
    idx = jnp.asarray(rng.choice(D, size=50, replace=False).astype(np.int32))
    vals = jnp.asarray(rng.normal(size=50).astype(np.float32) * 10)
    dense = jnp.zeros(D, jnp.float32).at[idx].set(vals)
    np.testing.assert_allclose(
        np.asarray(sketch_sparse(spec, idx, vals)),
        np.asarray(sketch_vec(spec, dense)),
        rtol=1e-5,
        atol=1e-5,
    )


def test_error_feedback_subtraction_cancels_heavy_mass(spec):
    """After e -= sketch_sparse(hh, est(hh)), estimates at hh drop from
    heavy scale (~100) to noise scale — the linearity property the
    server's error feedback relies on. Not exactly zero: when two heavy
    coords share a bucket in some row, the subtraction shifts that row's
    estimate and the median lands on another row's collision noise."""
    rng = np.random.default_rng(12)
    v, hh = planted_vector(D, 10, rng)
    table = sketch_vec(spec, v)
    hh_idx = jnp.asarray(hh.astype(np.int32))
    vals = estimate_at(spec, table, hh_idx)
    assert np.abs(np.asarray(vals)).min() > 50.0  # heavies seen at scale
    table2 = table - sketch_sparse(spec, hh_idx, vals)
    residual = np.abs(np.asarray(estimate_at(spec, table2, hh_idx)))
    assert residual.max() < 10.0, residual  # noise scale, not heavy scale


def test_unsketch_sparse_matches_dense(spec):
    rng = np.random.default_rng(13)
    v, _ = planted_vector(D, 15, rng)
    table = sketch_vec(spec, v)
    idx, vals = unsketch_sparse(spec, table, k=15)
    dense = unsketch(spec, table, k=15)
    np.testing.assert_allclose(
        np.asarray(dense)[np.asarray(idx)], np.asarray(vals), rtol=1e-6
    )


def test_bfloat16_sketch_recovers_heavy_hitters():
    """The bf16 MXU path must still recover planted heavy hitters (values
    within bf16-resolution tolerance)."""
    sp = CountSketch(d=D, c=C, r=R, seed=7, dtype=jnp.bfloat16)
    rng = np.random.default_rng(14)
    v, hh = planted_vector(D, 10, rng)
    rec = unsketch(sp, sketch_vec(sp, v), k=10)
    rec_idx = set(np.nonzero(np.asarray(rec))[0].tolist())
    assert set(hh.tolist()) <= rec_idx
    np.testing.assert_allclose(
        np.asarray(rec)[hh], np.asarray(v)[hh], rtol=0.2, atol=3.0
    )


def test_gpt2_scale_spec_geometry():
    """BASELINE config #4 scale (D ~= 124M): the realized table stays within
    a few percent of the requested num_rows*num_cols and its memory is the
    communication budget, not a D-sized buffer (sketch-mode memory check)."""
    d = 124_439_808  # GPT-2-small + specials, flattened
    sp = CountSketch(d=d, c=1_250_000, r=5, seed=1)
    r, c_actual = sp.table_shape
    assert r == 5
    assert abs(c_actual - 1_250_000) / 1_250_000 < 0.25
    table_mb = r * c_actual * 4 / 2**20
    assert table_mb < 40  # vs ~475 MB for one dense [D] f32 vector
    # per-coordinate mapping stays consistent at this scale
    idx = jnp.asarray([0, 1, d // 2, d - 1], jnp.int32)
    cols, signs = zip(*[
        __import__("commefficient_tpu.ops.countsketch", fromlist=["x"])
        ._row_cols_signs(sp, idx, row)
        for row in range(sp.r)
    ])
    for c in cols:
        assert int(jnp.max(c)) < c_actual and int(jnp.min(c)) >= 0


def test_jit_and_grad_safety(spec):
    """sketch/unsketch compile under jit and work on traced values."""
    v = jnp.ones(D, dtype=jnp.float32)

    @jax.jit
    def roundtrip(v):
        return unsketch(spec, sketch_vec(spec, v), k=10)

    out = roundtrip(v)
    assert out.shape == (D,)
    assert int(jnp.sum(out != 0)) <= 10


# ---- hash-family backstop (VERDICT r2 item 7) ----------------------------


@pytest.fixture(scope="module")
def pspec():
    """The 4-universal Mersenne-polynomial family (reference csvec's
    guarantee class), exposed as a lab A/B against the production fmix32."""
    return CountSketch(d=D, c=C, r=R, seed=7, hash_family="poly4")


def test_poly4_contract(pspec):
    """poly4 satisfies the same library contract as fmix32: linearity,
    planted-HH recovery, gather/matmul path agreement, determinism."""
    rng = np.random.default_rng(21)
    v, hh = planted_vector(D, 20, rng)
    table = sketch_vec(pspec, v)
    rec = unsketch(pspec, table, k=20)
    assert set(hh.tolist()) <= set(np.nonzero(np.asarray(rec))[0].tolist())
    a = jnp.asarray(rng.normal(size=D).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(sketch_vec(pspec, v + a)),
        np.asarray(sketch_vec(pspec, v) + sketch_vec(pspec, a)),
        rtol=1e-4, atol=1e-3,
    )
    idx = jnp.asarray(rng.choice(D, size=64, replace=False).astype(np.int32))
    np.testing.assert_allclose(
        np.asarray(estimate_all(pspec, table))[np.asarray(idx)],
        np.asarray(estimate_at(pspec, table, idx)),
        rtol=1e-5,
    )
    t2 = sketch_vec(CountSketch(d=D, c=C, r=R, seed=7, hash_family="poly4"), v)
    np.testing.assert_array_equal(np.asarray(table), np.asarray(t2))
    assert not np.array_equal(
        np.asarray(table),
        np.asarray(sketch_vec(
            CountSketch(d=D, c=C, r=R, seed=8, hash_family="poly4"), v
        )),
    )


@pytest.mark.parametrize("r", [1, 2, 3, 4, 5, 7])
def test_median_rows_matches_jnp_median(r):
    """The r4 min/max selection networks (estimate hot path) must be
    bit-equal to jnp.median for every row count, including the even-r and
    large-r fallback cases."""
    from commefficient_tpu.ops.countsketch import _median_rows

    rng = np.random.default_rng(r)
    x = jnp.asarray(rng.normal(size=(r, 4097)).astype(np.float32))
    np.testing.assert_array_equal(
        np.asarray(_median_rows(x)), np.asarray(jnp.median(x, axis=0))
    )


def test_poly4_rejects_out_of_field_inputs():
    """The 4-universality and uint64-exactness arguments both require
    x < p = 2^31-1 (ADVICE r3): inputs at/past the field size must fail
    loudly, not silently degrade the guarantee class."""
    from commefficient_tpu.ops.countsketch import _MERSENNE_P, _poly4_eval

    coeffs = np.array([3, 5, 7, 11], np.uint64)
    ok = _poly4_eval(np.array([0, 1, int(_MERSENNE_P) - 1], np.uint64), coeffs)
    assert ok.shape == (3,)
    with pytest.raises(ValueError, match="2\\^31-1"):
        _poly4_eval(np.array([int(_MERSENNE_P)], np.uint64), coeffs)


@pytest.mark.parametrize("family", ["fmix32", "poly4"])
def test_adversarial_strided_heavy_hitters(family):
    """Heavy hitters at layout-aligned strides — one per chunk at the SAME
    within-chunk offset (the worst structured input for a shared offset
    hash: all land in the same in-window slot of consecutive overlapping
    windows). The scramble must break the alignment; recovery stays
    clean for both hash families."""
    sp = CountSketch(d=D, c=C, r=R, seed=7, m=64, hash_family=family)
    rng = np.random.default_rng(33)
    v = rng.normal(0, 1.0, size=D).astype(np.float32)
    hh = (np.arange(20) * sp.chunk_m + 7) % D  # same offset, chunk stride
    assert len(set(hh.tolist())) == 20
    v[hh] += 100.0 * rng.choice([-1.0, 1.0], size=20)
    rec = unsketch(sp, sketch_vec(sp, jnp.asarray(v)), k=20)
    assert set(hh.tolist()) <= set(np.nonzero(np.asarray(rec))[0].tolist())


@pytest.mark.parametrize("family", ["fmix32", "poly4"])
def test_adversarial_equal_magnitude_ties(family):
    """A conv-layer-like cluster of EQUAL-magnitude, same-sign values (the
    tie pattern momentum builds on correlated filters). Estimates at the
    cluster must stay within ~collision noise of the true value — no
    constructive-interference blowup."""
    sp = CountSketch(d=D, c=C, r=R, seed=7, m=64, hash_family=family)
    rng = np.random.default_rng(34)
    v = rng.normal(0, 1.0, size=D).astype(np.float32)
    hh = np.arange(5000, 5128)  # 128 contiguous coords, one conv filter
    v[hh] = 50.0  # exactly tied
    est = np.asarray(
        estimate_at(sp, sketch_vec(sp, jnp.asarray(v)),
                    jnp.asarray(hh.astype(np.int32)))
    )
    np.testing.assert_allclose(est, 50.0, atol=15.0)


@pytest.mark.parametrize("family", ["fmix32", "poly4"])
def test_adversarial_feedback_iteration_bounded(family):
    """The FetchSGD extract-and-subtract loop on a FIXED structured input
    (the v3/v4 divergence reproducer, miniaturized): error table mass must
    stay bounded over the iterated rounds for both hash families (16 here — the documented r2 divergences showed up within ~6; the multi-epoch lab holds the long-horizon property). This is the
    multi-epoch-lab property reduced to a unit test."""
    sp = CountSketch(d=D, c=C, r=R, seed=7, m=64, hash_family=family)
    rng = np.random.default_rng(35)
    g = rng.normal(0, 1.0, size=D).astype(np.float32)
    g[np.arange(64) * 97 % D] += 30.0  # structured heavies, strided
    g = jnp.asarray(g)
    k = 64
    e = jnp.zeros(sp.table_shape, jnp.float32)
    ref = float(jnp.abs(sketch_vec(sp, g)).max())
    for _ in range(16):
        e = e + sketch_vec(sp, g)
        upd = unsketch(sp, e, k)
        e = e - sketch_vec(sp, upd)
    assert float(jnp.abs(e).max()) < 20.0 * ref, "feedback loop amplifying"
