"""asyncfed/ acceptance: buffered-asynchronous federation (PR 15).

The load-bearing claim is the correctness anchor: ``async_buffer=W,
async_concurrency=1, staleness_exponent=0`` reduces BIT-IDENTICALLY to the
synchronous round — same params, same losses, across compression modes,
error modes, and fedsim masking. Everything else (overlap, staleness
discounting, snapshot replay, schedule invariants, config grammar) is
pinned around that anchor:

- AsyncSchedule: anchor degenerates to one-cohort-per-update in launch
  order; at K < W or C > 1 every (cohort, slot) is consumed exactly once,
  in canonical sorted order, with bounded concurrency; the event
  simulation is a pure function of (seed, W, K, C, rate).
- Engine: zero retraces at any concurrency (the launch/apply programs
  compile once per rung and every update re-enters the same signatures);
  snapshot_extra/restore_extra replays the in-flight buffer verbatim so a
  restart from a snapshot is bit-identical to the uninterrupted run.
- Telemetry: under C=1 the async ledger bills exactly the synchronous
  byte count (same rounds x bytes_per_round), and the perf report carries
  the v8 ``async`` block.
"""

import json
import math
import os

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.asyncfed import AsyncFederation, AsyncSchedule, cohort_delays
from commefficient_tpu.data import FedDataset, FedSampler
from commefficient_tpu.models.losses import classification_loss
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.utils.config import Config


class TinyMLP(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.relu(nn.Dense(16)(x))
        return nn.Dense(4)(x)


BASE = dict(num_clients=12, num_workers=8, num_devices=8, local_batch_size=4,
            weight_decay=0.0, seed=5)

MODE_CONFIGS = {
    "uncompressed": dict(mode="uncompressed"),
    "sketch": dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                   k=20, num_rows=3, num_cols=200),
    "true_topk": dict(mode="true_topk", error_type="virtual", k=20),
    "local_topk": dict(mode="local_topk", error_type="local", k=20,
                       local_momentum=0.9),
}

N_ROUNDS = 3


def _setup(num_clients=12, n=400):
    rng = np.random.default_rng(0)
    w = rng.normal(size=(8, 4))
    x = rng.normal(size=(n, 8)).astype(np.float32)
    y = np.argmax(x @ w + 0.1 * rng.normal(size=(n, 4)), axis=1).astype(np.int32)
    ds = FedDataset({"x": x, "y": y}, num_clients, iid=True, seed=0)
    model = TinyMLP()
    params = model.init(jax.random.key(0), jnp.zeros((1, 8)))
    return ds, params, classification_loss(model.apply)


def _run_sync(cfg, num_rounds=N_ROUNDS, lr=0.3):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    losses = []
    for r in range(num_rounds):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, lr)
        losses.append(float(np.asarray(m["loss"])))
    return sess, losses


def _run_async(cfg, num_rounds=N_ROUNDS, lr=0.3):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    eng = AsyncFederation(cfg, sess, sampler, lambda s: lr, num_rounds,
                          steps_per_epoch=num_rounds).start()
    records = []
    try:
        for step, _lr, m in eng.epoch_rounds(0, 0):
            records.append((step, m))
    finally:
        eng.close()
    return sess, records, eng


def _anchor(extra):
    return Config(async_buffer=8, async_concurrency=1, staleness_exponent=0.0,
                  **extra, **BASE)


# ---------------------------------------------------------------------------
# AsyncSchedule: the host-side event simulation
# ---------------------------------------------------------------------------

def test_schedule_anchor_degenerates_to_sync_rounds():
    sch = AsyncSchedule(seed=5, num_workers=8, buffer_k=8, concurrency=1,
                        arrival_rate=1.0, num_updates=5)
    assert sch.num_cohorts == 5
    for u, spec in enumerate(sch.updates):
        assert spec.slots == tuple((u, s) for s in range(8))
        assert spec.staleness == (0,) * 8
        assert spec.launches_before == (u,)
        assert spec.buffer_fill_after == 0
    assert tuple(sch.launch_version) == tuple(range(5))
    assert sch.launched_before(3) == 3
    # the final update launches nothing new past itself
    assert sch.updates[-1].concurrent_after == 0


def test_schedule_rate_inf_is_instant_arrivals():
    d = cohort_delays(seed=5, cohort=2, num_workers=8, rate=math.inf)
    assert d.shape == (8,)
    assert np.all(d == 0.0)
    sch = AsyncSchedule(seed=5, num_workers=8, buffer_k=8, concurrency=1,
                        arrival_rate=math.inf, num_updates=4)
    for u, spec in enumerate(sch.updates):
        assert spec.slots == tuple((u, s) for s in range(8))
        assert spec.staleness == (0,) * 8


@pytest.mark.parametrize("k,c", [(5, 1), (4, 3), (8, 2)])
def test_schedule_consumes_every_slot_exactly_once(k, c):
    sch = AsyncSchedule(seed=5, num_workers=8, buffer_k=k, concurrency=c,
                        arrival_rate=2.0, num_updates=12)
    seen = set()
    for spec in sch.updates:
        assert len(spec.slots) == k
        assert list(spec.slots) == sorted(spec.slots), \
            "consumption order must be canonical (cohort, slot) sorted"
        for slot, st in zip(spec.slots, spec.staleness):
            assert slot not in seen, f"slot {slot} consumed twice"
            seen.add(slot)
            assert st >= 0
        assert 0 <= spec.concurrent_after <= c
        assert spec.buffer_fill_after >= 0
    # cohorts launch in order, versions are the update index at launch time
    launch_order = [cc for spec in sch.updates for cc in spec.launches_before]
    assert launch_order == sorted(launch_order)
    assert len(sch.launch_version) == sch.num_cohorts


def test_schedule_overlap_produces_staleness():
    sch = AsyncSchedule(seed=5, num_workers=8, buffer_k=4, concurrency=3,
                        arrival_rate=2.0, num_updates=10)
    stale = [st for spec in sch.updates for st in spec.staleness]
    assert max(stale) > 0, "C=3 overlap must produce stale contributions"


def test_schedule_is_deterministic():
    a = AsyncSchedule(seed=7, num_workers=8, buffer_k=3, concurrency=2,
                      arrival_rate=1.5, num_updates=9)
    b = AsyncSchedule(seed=7, num_workers=8, buffer_k=3, concurrency=2,
                      arrival_rate=1.5, num_updates=9)
    assert a.updates == b.updates
    assert tuple(a.launch_version) == tuple(b.launch_version)


@pytest.mark.parametrize("k", [0, 9])
def test_schedule_rejects_bad_buffer(k):
    with pytest.raises(ValueError, match="buffer_k"):
        AsyncSchedule(seed=5, num_workers=8, buffer_k=k, concurrency=1,
                      arrival_rate=1.0, num_updates=3)


def test_schedule_rejects_bad_concurrency():
    with pytest.raises(ValueError):
        AsyncSchedule(seed=5, num_workers=8, buffer_k=4, concurrency=0,
                      arrival_rate=1.0, num_updates=3)


# ---------------------------------------------------------------------------
# Config grammar
# ---------------------------------------------------------------------------

def test_config_async_rejections():
    with pytest.raises(ValueError, match="async_buffer"):
        Config(async_buffer=-1, **BASE)
    with pytest.raises(ValueError, match="num_workers"):
        Config(async_buffer=9, **BASE)
    with pytest.raises(ValueError, match="async_concurrency"):
        Config(async_buffer=4, async_concurrency=0, **BASE)
    with pytest.raises(ValueError, match="staleness_exponent"):
        Config(async_buffer=4, staleness_exponent=-0.5, **BASE)
    # knobs that silently do nothing without the engine are rejected
    with pytest.raises(ValueError, match="async_concurrency"):
        Config(async_concurrency=2, **BASE)
    with pytest.raises(ValueError, match="staleness_exponent"):
        Config(staleness_exponent=0.5, **BASE)
    # incompatible engines
    with pytest.raises(ValueError, match="fuse_clients|PER-CLIENT"):
        Config(async_buffer=4, fuse_clients=True, **BASE)
    with pytest.raises(ValueError, match="pipeline_depth"):
        Config(async_buffer=4, pipeline_depth=2, **BASE)
    with pytest.raises(ValueError, match="scan_rounds"):
        Config(async_buffer=4, scan_rounds=2, mode="sketch", k=20,
               num_rows=3, num_cols=200, error_type="virtual", **BASE)
    assert Config(async_buffer=8, **BASE).asyncfed_enabled
    assert not Config(**BASE).asyncfed_enabled


# ---------------------------------------------------------------------------
# THE anchor: K=W, C=1, alpha=0 == the synchronous round, bit-exact
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", sorted(MODE_CONFIGS))
def test_anchor_bit_identical_to_sync(mode):
    extra = MODE_CONFIGS[mode]
    sync_sess, sync_losses = _run_sync(Config(**extra, **BASE))
    async_sess, records, eng = _run_async(_anchor(extra))
    async_losses = [float(np.asarray(m["loss"])) for _, m in records]
    assert async_losses == sync_losses, f"{mode}: losses diverge"
    assert np.array_equal(np.asarray(async_sess.state.params_vec),
                          np.asarray(sync_sess.state.params_vec)), \
        f"{mode}: params not bit-identical at the anchor"
    assert eng.stats()["updates"] == N_ROUNDS


def test_anchor_bit_identical_under_fedsim_masking():
    extra = dict(MODE_CONFIGS["sketch"], availability="bernoulli",
                 dropout_prob=0.4)
    sync_sess, sync_losses = _run_sync(Config(**extra, **BASE))
    async_sess, records, _ = _run_async(_anchor(extra))
    async_losses = [float(np.asarray(m["loss"])) for _, m in records]
    assert async_losses == sync_losses
    assert np.array_equal(np.asarray(async_sess.state.params_vec),
                          np.asarray(sync_sess.state.params_vec))
    # fedsim scalars still ride the metrics, plus the async/* block
    _, m0 = records[0]
    for key in ("fedsim/participation_rate", "async/staleness_mean",
                "async/buffer_fill", "async/concurrent_cohorts",
                "async/effective_participation"):
        assert key in m0, f"missing {key}"


# ---------------------------------------------------------------------------
# overlap: genuine async behaviour, still zero retraces
# ---------------------------------------------------------------------------

def test_overlap_runs_with_zero_retraces():
    cfg = Config(async_buffer=4, async_concurrency=3, staleness_exponent=0.5,
                 availability="poisson", arrival_rate=2.0, dropout_prob=0.2,
                 **MODE_CONFIGS["sketch"], **BASE)
    sess, records, eng = _run_async(cfg, num_rounds=8)
    assert len(records) == 8
    for _, m in records:
        assert np.isfinite(float(np.asarray(m["loss"])))
    assert sess.retrace_sentinel.retraces == 0, \
        "async engine must reuse ONE compiled launch/apply pair per rung"
    st = eng.stats()
    assert st["updates"] == 8
    # 8 updates x K=4 slots consume 4 full cohorts' worth; the in-flight
    # window keeps a couple more launched past the last fire
    assert st["cohorts_launched"] >= 4
    stale = [float(m["async/staleness_mean"]) for _, m in records]
    assert max(stale) > 0, "C=3 must surface stale contributions"
    conc = [int(m["async/concurrent_cohorts"]) for _, m in records]
    assert max(conc) >= 2 and min(conc) >= 0


def test_staleness_discount_changes_the_trajectory():
    """alpha is live: with overlap, discounting stale rows must change the
    params (guards against the weight silently collapsing to 1.0)."""
    base = dict(async_buffer=4, async_concurrency=3, arrival_rate=2.0,
                **MODE_CONFIGS["uncompressed"], **BASE)
    s0, _, _ = _run_async(Config(staleness_exponent=0.0, **base), num_rounds=6)
    s1, _, _ = _run_async(Config(staleness_exponent=1.0, **base), num_rounds=6)
    assert not np.array_equal(np.asarray(s0.state.params_vec),
                              np.asarray(s1.state.params_vec))


# ---------------------------------------------------------------------------
# snapshot / restore: in-flight buffer replays verbatim
# ---------------------------------------------------------------------------

def test_snapshot_restore_replays_bit_identically():
    cfg = Config(async_buffer=4, async_concurrency=2, staleness_exponent=0.5,
                 arrival_rate=2.0, **MODE_CONFIGS["uncompressed"], **BASE)
    n, cut = 6, 3

    # uninterrupted reference
    ref_sess, ref_records, _ = _run_async(cfg, num_rounds=n)
    ref_losses = [float(np.asarray(m["loss"])) for _, m in ref_records]

    # same run, but snapshot at `cut` and restart from the blob: the
    # restored pending outputs must be the SAME arrays, so the tail of the
    # run is bit-identical to the uninterrupted one
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    eng = AsyncFederation(cfg, sess, sampler, lambda s: 0.3, n,
                          steps_per_epoch=n).start()
    losses = []
    try:
        for step, _lr, m in eng.epoch_rounds(0, 0):
            losses.append(float(np.asarray(m["loss"])))
            if step == cut - 1:
                break
        blob = eng.snapshot_extra()
        assert int(blob["update"]) == cut
        assert blob["pending"], "C=2 snapshot must carry in-flight cohorts"
        # round-trip through JSON-ish copy semantics: restore and restart
        eng.restore_extra(blob)
        eng.restart(cut)
        for step, _lr, m in eng.epoch_rounds(0, cut):
            losses.append(float(np.asarray(m["loss"])))
    finally:
        eng.close()
    assert losses == ref_losses
    assert np.array_equal(np.asarray(sess.state.params_vec),
                          np.asarray(ref_sess.state.params_vec)), \
        "restored in-flight buffer must replay bit-identically"
    assert eng.stats()["restarts"] == 1


def test_cold_restart_without_blob_is_deterministic():
    """A plain restart (no snapshot blob) rebuilds the in-flight window by
    relaunching the same cohorts at the same versions — deterministic, and
    at the anchor (C=1) it is indistinguishable from never restarting."""
    cfg = _anchor(MODE_CONFIGS["uncompressed"])
    n, cut = 4, 2
    ref_sess, ref_records, _ = _run_async(cfg, num_rounds=n)

    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    eng = AsyncFederation(cfg, sess, sampler, lambda s: 0.3, n,
                          steps_per_epoch=n).start()
    try:
        for step, _lr, m in eng.epoch_rounds(0, 0):
            if step == cut - 1:
                break
        eng.restart(cut)  # no restore_extra: cold window rebuild
        for step, _lr, m in eng.epoch_rounds(0, cut):
            pass
    finally:
        eng.close()
    assert np.array_equal(np.asarray(sess.state.params_vec),
                          np.asarray(ref_sess.state.params_vec))


# ---------------------------------------------------------------------------
# telemetry: C=1 byte parity with the sync ledger + v8 perf report
# ---------------------------------------------------------------------------

def test_anchor_ledger_bills_exactly_the_sync_bytes(tmp_path):
    """Through the REAL train loop: the async run's comm_ledger must equal
    the synchronous twin's byte-for-byte under C=1, and the perf report is
    engine="async" with the v8 async block."""
    from commefficient_tpu.train.cv_train import train_loop
    from commefficient_tpu.utils.logging import MetricsWriter

    ledgers, reports = {}, {}
    for tag, extra in (("sync", {}),
                       ("async", dict(async_buffer=8, async_concurrency=1,
                                      staleness_exponent=0.0))):
        cfg = Config(telemetry_level=1, num_epochs=1, pivot_epoch=1,
                     lr_scale=0.1, **MODE_CONFIGS["sketch"], **extra, **BASE)
        ds, params, loss_fn = _setup(cfg.num_clients, n=160)
        test_ds = FedDataset({"x": ds.data["x"][:40], "y": ds.data["y"][:40]},
                             1, seed=0)
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=cfg.num_workers,
                             local_batch_size=cfg.sampler_batch_size, seed=1)
        run_dir = str(tmp_path / f"run_{tag}")
        writer = MetricsWriter(run_dir, cfg=cfg)
        try:
            train_loop(cfg, sess, sampler, test_ds, writer, eval_batch_size=32)
        finally:
            writer.close()
        with open(os.path.join(run_dir, "comm_ledger.json")) as f:
            ledgers[tag] = json.load(f)
        with open(os.path.join(run_dir, "perf_report.json")) as f:
            reports[tag] = json.load(f)

    for key in ("rounds", "cum_up_bytes", "cum_down_bytes", "cum_bytes"):
        assert ledgers["async"][key] == ledgers["sync"][key], \
            f"C=1 async ledger must reconcile with sync: {key}"
    assert reports["async"]["engine"] == "async"
    assert reports["async"]["async"] == {
        "buffer": 8, "concurrency": 1, "staleness_exponent": 0.0}
    assert reports["sync"]["engine"] == "replicated"
    assert "async" not in reports["sync"]


# ---------------------------------------------------------------------------
# double-buffered rounds (ISSUE 16): deferred fence, same bits
# ---------------------------------------------------------------------------

def _run_async_spans(cfg, tmp_path, num_rounds=N_ROUNDS, lr=0.3,
                     ladder_rounds=None):
    """_run_async with a live PhaseSpans attached to session AND engine —
    the double-buffered fence discipline only executes with spans armed
    (without them there is nothing to defer), so these tests must run it
    for real."""
    from commefficient_tpu.telemetry.spans import PhaseSpans

    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    if ladder_rounds:
        from commefficient_tpu.control import build_controller

        ctrl = build_controller(cfg, sess, num_rounds=ladder_rounds)
        ctrl.prewarm(sampler, lr)
    spans = PhaseSpans(str(tmp_path), start_step=2, num_steps=num_rounds)
    sess.spans = spans
    eng = AsyncFederation(cfg, sess, sampler, lambda s: lr, num_rounds,
                          steps_per_epoch=num_rounds, spans=spans).start()
    records = []
    try:
        for step, _lr, m in eng.epoch_rounds(0, 0):
            records.append((step, m))
    finally:
        eng.close()
    return sess, records, eng, spans


@pytest.mark.parametrize("mode", [
    pytest.param("uncompressed", marks=pytest.mark.slow),
    "sketch",  # headline mode holds the default-tier pin (PR-12 precedent)
])
def test_double_buffer_anchor_bit_identical_to_sync(mode, tmp_path):
    """The apply fence parks behind the next cohort's launches, but the
    device programs dispatch in the same order — K=W, C=1, alpha=0 must
    still reduce to the synchronous round bit for bit."""
    extra = MODE_CONFIGS[mode]
    sync_sess, sync_losses = _run_sync(Config(**extra, **BASE))
    cfg = _anchor(dict(extra, async_double_buffer=True))
    async_sess, records, eng, spans = _run_async_spans(cfg, tmp_path)
    async_losses = [float(np.asarray(m["loss"])) for _, m in records]
    assert async_losses == sync_losses
    assert np.array_equal(np.asarray(async_sess.state.params_vec),
                          np.asarray(sync_sess.state.params_vec)), \
        f"{mode}: double-buffered anchor not bit-identical"
    # the deferred discipline actually ran: applies record as dispatch
    # spans (not collective-fenced applies) and the parked fences drained
    names = [ev["name"] for ev in spans.events]
    assert "async_apply_dispatch" in names
    assert "async_apply_drain" in names
    assert "async_apply" not in names, \
        "double-buffer mode must not record sequential apply spans"
    # drain spans are the collective-tagged ones
    for ev in spans.events:
        if ev["name"] == "async_apply_drain":
            assert ev["args"].get("collective") is True
        if ev["name"] == "async_apply_dispatch":
            assert "collective" not in ev["args"]


def test_double_buffer_close_drains_parked_fence(tmp_path):
    """close() (and snapshot_extra) must drain the parked fence — the
    last update's loss cannot stay un-synced past the engine's life."""
    cfg = _anchor(dict(MODE_CONFIGS["uncompressed"],
                       async_double_buffer=True))
    _sess, records, eng, spans = _run_async_spans(cfg, tmp_path)
    assert eng._deferred is None, "close() left a parked fence"
    drains = [ev for ev in spans.events
              if ev["name"] == "async_apply_drain"]
    assert len(drains) == len(records), \
        "every deferred apply fence must drain exactly once"


def test_double_buffer_snapshot_restore_replays_bit_identically(tmp_path):
    """The vault riders under double buffering: snapshot_extra drains the
    parked fence first, and the restored in-flight window replays the
    tail bit-identically — the rollback/recovery path stays exact."""
    extra = dict(MODE_CONFIGS["uncompressed"], async_double_buffer=True)
    cfg = Config(async_buffer=4, async_concurrency=2,
                 staleness_exponent=0.5, arrival_rate=2.0, **extra, **BASE)
    n, cut = 6, 3

    ref_sess, ref_records, _, _ = _run_async_spans(
        cfg, tmp_path / "ref", num_rounds=n)
    ref_losses = [float(np.asarray(m["loss"])) for _, m in ref_records]

    from commefficient_tpu.telemetry.spans import PhaseSpans

    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.sampler_batch_size, seed=1)
    spans = PhaseSpans(str(tmp_path / "cut"), start_step=2, num_steps=n)
    sess.spans = spans
    eng = AsyncFederation(cfg, sess, sampler, lambda s: 0.3, n,
                          steps_per_epoch=n, spans=spans).start()
    losses = []
    try:
        for step, _lr, m in eng.epoch_rounds(0, 0):
            losses.append(float(np.asarray(m["loss"])))
            if step == cut - 1:
                break
        blob = eng.snapshot_extra()
        assert eng._deferred is None, "snapshot_extra left a parked fence"
        eng.restore_extra(blob)
        eng.restart(cut)
        for step, _lr, m in eng.epoch_rounds(0, cut):
            losses.append(float(np.asarray(m["loss"])))
    finally:
        eng.close()
    assert losses == ref_losses
    assert np.array_equal(np.asarray(sess.state.params_vec),
                          np.asarray(ref_sess.state.params_vec))


def test_double_buffer_zero_retraces_across_rung_switches(tmp_path):
    """A mid-run ladder switch quiesces the window and recompiles the
    rung's launch/apply pair ONCE; the deferred fence must neither leak
    across the switch nor force extra retraces. telemetry_level=1 also
    exercises the new xla/exposed_collective_ms scalar end-to-end."""
    n = 6
    cfg = Config(async_buffer=8, async_concurrency=1,
                 staleness_exponent=0.0, async_double_buffer=True,
                 mode="local_topk", error_type="local",
                 topk_method="threshold", telemetry_level=1,
                 control_policy="fixed", control_schedule="0-2=0,3-=1",
                 ladder="k=20,10", **BASE)
    sess, records, eng, spans = _run_async_spans(
        cfg, tmp_path, num_rounds=n, ladder_rounds=n)
    assert len(records) == n
    for _, m in records:
        assert np.isfinite(float(np.asarray(m["loss"])))
    assert eng.quiesces == 1, "the ladder switch must quiesce the window"
    assert sess.retrace_sentinel.retraces == 0, \
        "double buffering must not add retraces across rung switches"
    rungs = [float(np.asarray(m["control/rung"])) for _, m in records]
    assert rungs == [0, 0, 0, 1, 1, 1]
    # the v9 scalar rides the metrics whenever spans are armed
    for _, m in records:
        assert float(np.asarray(m["xla/retraces"])) == 0
        assert float(m["xla/exposed_collective_ms"]) >= 0.0
