"""Model-layer tests: shapes, param counts, grad flow, loss conventions."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from commefficient_tpu.models import (
    ResNet9,
    fixup_resnet50,
    GPT2DoubleHeads,
    classification_loss,
    gpt2_double_heads_loss,
)
from commefficient_tpu.models.gpt2 import gpt2_tiny_config
from commefficient_tpu.ops import ravel_params


def _n_params(params):
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))


def test_resnet9_shapes_and_param_count():
    model = ResNet9(num_classes=10)
    x = jnp.zeros((4, 32, 32, 3))
    params = model.init(jax.random.key(0), x)
    logits = model.apply(params, x)
    assert logits.shape == (4, 10)
    assert logits.dtype == jnp.float32
    # reference ResNet-9 is ~6.5M params (SURVEY.md §2)
    n = _n_params(params)
    assert 6_000_000 < n < 7_500_000, n


@pytest.mark.slow  # training sanity is held (faster) by the e2e entry
# tests; this isolates the bare model+grad path
def test_resnet9_loss_decreases_one_sgd_step():
    model = ResNet9(num_classes=10, width=16)
    rng = jax.random.key(0)
    x = jax.random.normal(rng, (16, 32, 32, 3))
    y = jax.random.randint(rng, (16,), 0, 10)
    params = model.init(rng, x)
    loss_fn = classification_loss(model.apply)
    batch = {"x": x, "y": y}

    (l0, m0), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
    params2 = jax.tree.map(lambda p, g: p - 0.5 * g, params, grads)
    l1, _ = loss_fn(params2, batch)
    assert float(l1) < float(l0)
    assert 0 <= float(m0["correct"]) <= 16


def test_resnet9_flat_vector_roundtrip():
    model = ResNet9(num_classes=10, width=8)
    params = model.init(jax.random.key(0), jnp.zeros((1, 32, 32, 3)))
    vec, unravel = ravel_params(params)
    params2 = unravel(vec)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params2)):
        np.testing.assert_allclose(np.asarray(a, np.float32), np.asarray(b), rtol=1e-6)


@pytest.mark.slow  # r5 tier budget: structural init check (~40s of
# compile); the model is exercised at full scale by every ImageNet
# evidence run and the imagenet-augment equivalence tests stay default
def test_fixup_resnet50_forward():
    model = fixup_resnet50(num_classes=10)
    x = jnp.zeros((2, 64, 64, 3))  # small spatial size still exercises all stages
    params = model.init(jax.random.key(0), x)
    logits = model.apply(params, x)
    assert logits.shape == (2, 10)
    # Fixup: zero-init classifier -> logits exactly zero at init
    np.testing.assert_allclose(np.asarray(logits), 0.0)


def test_gpt2_double_heads_shapes_and_loss():
    cfg = gpt2_tiny_config()
    model = GPT2DoubleHeads(cfg)
    B, N, T = 2, 2, 16
    rng = jax.random.key(0)
    input_ids = jax.random.randint(rng, (B, N, T), 0, cfg.vocab_size)
    mc_token_ids = jnp.full((B, N), T - 1)
    params = model.init(rng, input_ids, input_ids * 0, mc_token_ids)
    lm_logits, mc_logits = model.apply(params, input_ids, input_ids * 0, mc_token_ids)
    assert lm_logits.shape == (B, N, T, cfg.vocab_size)
    assert mc_logits.shape == (B, N)

    lm_labels = jnp.where(
        jax.random.bernoulli(rng, 0.5, (B, N, T)), input_ids, -100
    )
    batch = {
        "input_ids": input_ids,
        "token_type_ids": input_ids * 0,
        "lm_labels": lm_labels,
        "mc_token_ids": mc_token_ids,
        "mc_labels": jnp.array([0, 1]),
    }
    loss_fn = gpt2_double_heads_loss(model.apply)
    (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch)
    assert jnp.isfinite(loss)
    assert metrics["lm_loss"] > 0
    # grads flow to embeddings and mc head
    g, _ = ravel_params(grads)
    assert float(jnp.abs(g).sum()) > 0


@pytest.mark.slow  # r20 tier budget (~20 s of width-8 ResNet-9 grads):
# the bf16/f32 agreement contract also rides tier-1 through the
# compressed-path bf16 composition pins (sketch tables, overlap)
def test_compute_dtype_modes():
    """The three compute modes are genuinely different graphs that agree
    to bf16 resolution: "float32" (module dtype f32, true f32 compute)
    vs "bfloat16" (module bf16 + loss-boundary param cast, full-bf16
    stream). Grads w.r.t. the f32 master params come back f32 in both."""
    from commefficient_tpu.models.losses import model_dtype

    m_f32 = ResNet9(num_classes=10, width=8, dtype=model_dtype("float32"))
    m_bf16 = ResNet9(num_classes=10, width=8, dtype=model_dtype("bfloat16"))
    rng = jax.random.key(0)
    x = jax.random.normal(rng, (8, 32, 32, 3))
    y = jax.random.randint(rng, (8,), 0, 10)
    params = m_f32.init(rng, x)  # param dtypes are f32 in every mode
    batch = {"x": x, "y": y}
    lf32 = classification_loss(m_f32.apply, compute_dtype="float32")
    lbf16 = classification_loss(m_bf16.apply, compute_dtype="bfloat16")
    (l32, _), g32 = jax.value_and_grad(lf32, has_aux=True)(params, batch)
    (l16, _), g16 = jax.value_and_grad(lbf16, has_aux=True)(params, batch)
    assert np.isfinite(float(l16))
    # different precision paths must actually differ...
    assert float(l16) != float(l32)
    # ...but agree to bf16 resolution
    assert abs(float(l16) - float(l32)) / abs(float(l32)) < 0.05
    flat16, _ = jax.flatten_util.ravel_pytree(g16)
    flat32, _ = jax.flatten_util.ravel_pytree(g32)
    assert flat16.dtype == jnp.float32  # master-grad dtype preserved
    cos = float(
        jnp.vdot(flat16, flat32)
        / (jnp.linalg.norm(flat16) * jnp.linalg.norm(flat32))
    )
    assert cos > 0.98, cos
