"""Mesh helpers: multi-host bring-up guards (parallel/mesh.py)."""

import os
from unittest import mock

import jax


def test_initialize_distributed_noop_single_host():
    from commefficient_tpu.parallel.mesh import initialize_distributed

    clean = {
        k: None
        for k in (
            "JAX_COORDINATOR_ADDRESS",
            "COORDINATOR_ADDRESS",
            "MEGASCALE_COORDINATOR_ADDRESS",
            "TPU_WORKER_HOSTNAMES",
        )
    }
    env = {k: v for k, v in os.environ.items() if k not in clean}
    with mock.patch.dict(os.environ, env, clear=True):
        assert initialize_distributed() is False


def test_initialize_distributed_ignores_single_hostname():
    """The axon tunnel injects TPU_WORKER_HOSTNAMES=localhost; one host is
    not a pod, and must not trigger jax.distributed.initialize()."""
    from commefficient_tpu.parallel.mesh import initialize_distributed

    with mock.patch.dict(os.environ, {"TPU_WORKER_HOSTNAMES": "localhost"}):
        assert initialize_distributed() is False


def test_initialize_distributed_after_backend_init_warns_not_raises(recwarn):
    """With a real coordinator configured but the backend already up (e.g.
    called twice, or from tests), degrade to single-process with a warning
    instead of RuntimeError (regression: r2 gpt2_train e2e failure)."""
    from commefficient_tpu.parallel.mesh import initialize_distributed

    jax.devices()  # ensure the backend is initialized
    with mock.patch.dict(
        os.environ, {"TPU_WORKER_HOSTNAMES": "host-a,host-b"}
    ):
        assert initialize_distributed() is False
    assert any("already initialized" in str(w.message) for w in recwarn.list)
