"""PowerSGD compressor tests (compress/powersgd.py).

The oracle contract: at full rank the Gram-Schmidt power iteration
reconstructs the matricized accumulator EXACTLY (P_hat spans range(M)), so
mode=powersgd must reduce to the uncompressed round; at low rank it must
train under lr-scaled error feedback with the same Alg-1 banking semantics
as the other modes (varying-lr regression)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from test_round import BASE, _final_vec, _ignore_batch_like, _run, _setup

from commefficient_tpu.compress.powersgd import gram_schmidt, matrix_shape
from commefficient_tpu.data import FedSampler
from commefficient_tpu.ops import ravel_params
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.utils.config import Config


def _full_rank():
    ds, params, loss_fn = _setup()
    d = int(ravel_params(params)[0].size)
    n, m = matrix_shape(d)
    return min(n, m), d


def test_gram_schmidt_orthonormalizes():
    rng = np.random.default_rng(0)
    P = jnp.asarray(rng.normal(size=(40, 6)).astype(np.float32))
    Q = np.asarray(gram_schmidt(P))
    np.testing.assert_allclose(Q.T @ Q, np.eye(6), atol=1e-5)
    # spans the same subspace: projecting P onto Q reproduces P
    np.testing.assert_allclose(Q @ (Q.T @ np.asarray(P)), np.asarray(P),
                               atol=1e-4)


def test_gram_schmidt_rank_deficient_collapses_to_zero():
    """Dependent columns must become exact zeros, not amplified noise."""
    rng = np.random.default_rng(1)
    a = rng.normal(size=(40, 1)).astype(np.float32)
    P = jnp.asarray(np.concatenate([a, 2.0 * a, a + 1e-9], axis=1))
    Q = np.asarray(gram_schmidt(P))
    assert np.abs(Q[:, 1]).max() < 1e-5
    np.testing.assert_allclose(np.linalg.norm(Q[:, 0]), 1.0, atol=1e-5)


@pytest.mark.parametrize("momentum", [0.0, 0.9])
def test_full_rank_with_error_feedback_equals_uncompressed(momentum):
    """Rank-sweep oracle, top end: r = min(n, m) reconstructs exactly, so
    the error bank stays zero and the round IS the uncompressed round."""
    rank, _ = _full_rank()
    cfg_p = Config(mode="powersgd", error_type="virtual",
                   powersgd_rank=rank, virtual_momentum=momentum, **BASE)
    cfg_u = Config(mode="uncompressed", virtual_momentum=momentum, **BASE)
    sp, lp = _run(cfg_p)
    su, lu = _run(cfg_u)
    np.testing.assert_allclose(lp, lu, rtol=1e-4)
    # exact in exact arithmetic; fp32 GS rounding compounds over 5 rounds
    np.testing.assert_allclose(_final_vec(sp), _final_vec(su), atol=5e-4)


def test_full_rank_no_error_equals_uncompressed():
    rank, _ = _full_rank()
    cfg_p = Config(mode="powersgd", error_type="none", powersgd_rank=rank,
                   virtual_momentum=0.9, **BASE)
    cfg_u = Config(mode="uncompressed", virtual_momentum=0.9, **BASE)
    sp, _ = _run(cfg_p)
    su, _ = _run(cfg_u)
    # fp32 GS rounding headroom, as above
    np.testing.assert_allclose(_final_vec(sp), _final_vec(su), atol=5e-4)


@pytest.mark.parametrize("rank", [1, 4])
def test_low_rank_trains_with_error_feedback(rank):
    """Rank-sweep oracle, low end: heavy compression still converges under
    error feedback (the PowerSGD paper's core claim)."""
    cfg = Config(mode="powersgd", error_type="virtual", powersgd_rank=rank,
                 virtual_momentum=0.9, **BASE)
    _, losses = _run(cfg, n_rounds=15)
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.9


def test_error_feedback_banks_lr_at_accumulation_powersgd():
    """Same Alg-1 contract as sketch/true_topk (round.py docstring
    DECISION): residual banked at round-1's lr applies at THAT lr — a
    zero-gradient round 2 must be lr2-invariant."""
    cfg = Config(mode="powersgd", error_type="virtual", powersgd_rank=2,
                 **BASE)
    finals = []
    for lr2 in (0.01, 1.0):
        ds, params, loss_fn = _setup(cfg.num_clients)
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=cfg.num_workers,
                             local_batch_size=cfg.local_batch_size, seed=1)
        ids, batch = sampler.sample_round(0)
        sess.train_round(ids, batch, lr=0.3)
        sess.train_round(ids, _ignore_batch_like(batch), lr=lr2)
        finals.append(_final_vec(sess))
    np.testing.assert_allclose(finals[0], finals[1], atol=1e-6)


def test_warm_start_carries_q_in_fedstate():
    cfg = Config(mode="powersgd", error_type="virtual", powersgd_rank=3,
                 powersgd_warm_start=True, **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    q0 = np.asarray(sess.state.comp).copy()
    d = int(ravel_params(params)[0].size)
    n, m = matrix_shape(d)
    assert q0.shape == (m, 3)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ids, batch = sampler.sample_round(0)
    sess.train_round(ids, batch, 0.2)
    q1 = np.asarray(sess.state.comp)
    assert np.abs(q1 - q0).max() > 1e-6  # the power iteration moved Q

    # warm_start=False carries NO state at all: Q is resampled from
    # (seed, step) each round, so FedState/checkpoints hold ()
    cfg2 = cfg.replace(powersgd_warm_start=False)
    sess2 = FederatedSession(cfg2, params, loss_fn)
    assert sess2.state.comp == ()
    sess2.train_round(ids, batch, 0.2)
    assert sess2.state.comp == ()
    assert np.isfinite(_final_vec(sess2)).all()


def test_warm_start_changes_trajectory_at_low_rank():
    kw = dict(mode="powersgd", error_type="virtual", powersgd_rank=1,
              virtual_momentum=0.9)
    s_warm, _ = _run(Config(powersgd_warm_start=True, **kw, **BASE))
    s_cold, _ = _run(Config(powersgd_warm_start=False, **kw, **BASE))
    assert np.abs(_final_vec(s_warm) - _final_vec(s_cold)).max() > 0


def test_bytes_per_round_reports_factored_downlink():
    cfg = Config(mode="powersgd", error_type="virtual", powersgd_rank=2,
                 **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    d = sess.grad_size
    n, m = matrix_shape(d)
    bpr = sess.bytes_per_round()
    assert bpr["upload_floats"] == d  # server-side compression, like true_topk
    assert bpr["download_floats"] == 2 * (n + m)
    assert bpr["download_bytes"] == 4 * 2 * (n + m)


def test_powersgd_rejects_unsupported_combinations():
    with pytest.raises(ValueError, match="do_topk_down"):
        Config(mode="powersgd", do_topk_down=True, **BASE)
    with pytest.raises(ValueError, match="dampening"):
        Config(mode="powersgd", momentum_dampening=True, **BASE)
    with pytest.raises(ValueError, match="powersgd_rank"):
        Config(mode="powersgd", powersgd_rank=0, **BASE)
    ds, params, loss_fn = _setup()
    with pytest.raises(NotImplementedError):
        FederatedSession(
            Config(mode="powersgd", error_type="local", **BASE),
            params, loss_fn,
        )
    with pytest.raises(NotImplementedError, match="fsdp"):
        FederatedSession(
            Config(mode="powersgd", topk_method="threshold", fsdp=True,
                   **BASE),
            params, loss_fn,
        )
