"""Scan-over-rounds device-resident engine (pipeline/scan_engine.py).

The engine's one non-negotiable claim mirrors the prefetcher's: ANY
``--scan_rounds K`` produces the same training as per-round dispatch —
params bit-equal AND the drained scalar sequence identical — because the
scan body is the SAME unjitted index-round closure the per-round path
wraps, every staged input is a pure function of the round index, and
blocks chop at every boundary where the runner observes device state
(checkpoint saves, vault snapshots, epoch ends). Pinned here at engine
level (K=2/3/5 vs the direct index path, fedsim masks included), at
block-plan level (chopping), and through the REAL shared runner
(checkpoint + resume bit-exactness vs the synchronous loop). Config
refuses what a scanned block cannot honor (control plane, pipeline
depth, preemption, host-batch paths) with the blocker named.
"""

import json
import os

import numpy as np
import pytest
from test_round import BASE, _setup

from commefficient_tpu.data import FedSampler
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.pipeline import ScanRounds
from commefficient_tpu.utils.config import Config


def _cfg(**kw):
    return Config(**{**BASE, "mode": "sketch", "error_type": "virtual",
                     "virtual_momentum": 0.9, "k": 40, "num_rows": 3,
                     "num_cols": 256, "topk_method": "threshold", **kw})


def _build(cfg):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    assert sess.maybe_attach_data(ds, sampler), (
        "TinyMLP data must take the device-resident path"
    )
    return sess, sampler


def _lr_fn(s):
    return 0.3 - 0.01 * s


# ---------------------------------------------------------------------------
# engine level: K > 1 == per-round dispatch, bitwise
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("K", [3])  # K=2/5 twins are slow-marked below:
# one K in tier keeps the 870 s budget; the block-plan unit tests cover
# every chop length combinatorially at zero dispatch cost
def test_scan_engine_bit_exact_vs_per_round_dispatch(K):
    n = 7
    cfg = _cfg(telemetry_level=1)
    sess_a, sampler_a = _build(cfg)
    seq_a = []
    for r in range(n):
        ids, idx, plan = sampler_a.sample_round_indices(r)
        m = sess_a.train_round_indices(ids, idx, plan, _lr_fn(r))
        seq_a.append(float(np.asarray(m["loss"])))

    sess_b, sampler_b = _build(_cfg(telemetry_level=1, scan_rounds=K))
    eng = ScanRounds(_cfg(telemetry_level=1, scan_rounds=K), sess_b,
                     sampler_b, _lr_fn, num_rounds=n,
                     steps_per_epoch=n).start(0)
    out = list(eng.epoch_rounds(0, 0))
    assert [s for s, _, _ in out] == list(range(n))
    np.testing.assert_array_equal(np.asarray(sess_a.state.params_vec),
                                  np.asarray(sess_b.state.params_vec))
    np.testing.assert_array_equal(
        np.asarray(seq_a),
        np.asarray([float(np.asarray(m["loss"])) for _, _, m in out]),
    )
    # telemetry rides: every yielded dict names the block length
    lens = [float(m["pipeline/scan_rounds_per_dispatch"]) for _, _, m in out]
    assert max(lens) == float(min(K, n))
    assert eng.stats()["dispatches"] < n  # really amortized


@pytest.mark.slow
@pytest.mark.parametrize("K", [2, 5])
def test_scan_engine_bit_exact_more_lengths(K):
    test_scan_engine_bit_exact_vs_per_round_dispatch(K)


def test_scan_engine_fedsim_masks_bit_exact():
    """Staged [L, W] fedsim envs scan bit-identically to per-round env
    realization (masking + live-count renorm inside the scanned body)."""
    n, K = 6, 4
    kw = dict(availability="bernoulli", dropout_prob=0.3, telemetry_level=1)
    sess_a, sampler_a = _build(_cfg(**kw))
    for r in range(n):
        ids, idx, plan = sampler_a.sample_round_indices(r)
        sess_a.train_round_indices(ids, idx, plan, _lr_fn(r))

    cfg_s = _cfg(scan_rounds=K, **kw)
    sess_b, sampler_b = _build(cfg_s)
    eng = ScanRounds(cfg_s, sess_b, sampler_b, _lr_fn, num_rounds=n,
                     steps_per_epoch=n).start(0)
    out = list(eng.epoch_rounds(0, 0))
    assert len(out) == n
    np.testing.assert_array_equal(np.asarray(sess_a.state.params_vec),
                                  np.asarray(sess_b.state.params_vec))
    # host fedsim stats ride each round's dict like the direct path's
    assert all("fedsim/participation_rate" in m for _, _, m in out)


# ---------------------------------------------------------------------------
# block plan: chopping at state-observation boundaries
# ---------------------------------------------------------------------------

def test_blocks_chop_at_checkpoint_and_snapshot_gates(tmp_path):
    cfg = _cfg(scan_rounds=8, checkpoint_dir=str(tmp_path),
               checkpoint_every=5, telemetry_level=1,
               recover_policy="retry", snapshot_every=4)
    sess, sampler = _build(cfg)
    eng = ScanRounds(cfg, sess, sampler, _lr_fn, num_rounds=40,
                     steps_per_epoch=40)
    blocks = list(eng._blocks(0, 20))
    # every block END must land on a gate or a K/epoch boundary, and no
    # block may CROSS a multiple of 5 (checkpoint) or 4 (snapshot):
    # will_save/will_snapshot at step = round+1 see true block-end state
    for start, length in blocks:
        end = start + length
        assert length >= 1 and length <= 8
        for g in (5, 4):
            assert (start // g) == ((end - 1) // g), (
                f"block [{start}, {end}) crosses a gate multiple of {g}"
            )
    assert [b[0] for b in blocks][0] == 0
    assert sum(b[1] for b in blocks) == 20


def test_blocks_no_gates_use_full_K():
    cfg = _cfg(scan_rounds=4)
    sess, sampler = _build(cfg)
    eng = ScanRounds(cfg, sess, sampler, _lr_fn, num_rounds=10,
                     steps_per_epoch=10)
    assert list(eng._blocks(0, 10)) == [(0, 4), (4, 4), (8, 2)]


# ---------------------------------------------------------------------------
# the REAL shared runner: checkpoint + resume, scan vs synchronous
# ---------------------------------------------------------------------------

def _scalar_sequence(logdir):
    out = []
    for root, _, files in os.walk(logdir):
        for f in sorted(files):
            if f != "metrics.jsonl":
                continue
            with open(os.path.join(root, f)) as fh:
                for line in fh:
                    rec = json.loads(line)
                    if "name" not in rec:
                        continue
                    if rec["name"].startswith(
                        ("pipeline/", "trace/", "xla/exposed_collective_ms")
                    ):
                        # scan gauges exist only at K > 1; the exposure
                        # scalar (v9) and trace/* attribution (v11) are
                        # host wall-clock, never bit-equal
                        continue
                    out.append((rec["name"], rec["value"], rec["step"]))
    return out


def test_runner_scan_bit_exact_and_resume(tmp_path):
    from commefficient_tpu.train.cv_train import train_loop
    from commefficient_tpu.utils.checkpoint import FedCheckpointer
    from commefficient_tpu.utils.logging import MetricsWriter

    from commefficient_tpu.data import FedDataset

    ds, params, loss_fn = _setup(12)
    test_ds = FedDataset({"x": ds.data["x"][:40], "y": ds.data["y"][:40]},
                         1, seed=0)

    def run(scan, tag, resume=False):
        cfg = _cfg(telemetry_level=1, perf_audit=False, num_epochs=1,
                   pivot_epoch=1, lr_scale=0.1,
                   checkpoint_dir=str(tmp_path / f"ckpt{tag}"),
                   checkpoint_every=5, scan_rounds=scan, resume=resume)
        sess, sampler = _build(cfg)
        run_dir = str(tmp_path / f"run{tag}" / ("res" if resume else "full"))
        writer = MetricsWriter(run_dir, cfg=cfg)
        ck = FedCheckpointer(cfg)
        try:
            train_loop(cfg, sess, sampler, test_ds, writer,
                       eval_batch_size=32, checkpointer=ck)
        finally:
            ck.close()
            writer.close()
        return sess, run_dir

    s0, dir0 = run(0, "_k0")
    s3, dir3 = run(3, "_k3")
    np.testing.assert_array_equal(np.asarray(s0.state.params_vec),
                                  np.asarray(s3.state.params_vec))
    seq0, seq3 = _scalar_sequence(dir0), _scalar_sequence(dir3)
    assert seq0 and seq0 == seq3
    assert s3.retrace_sentinel.retraces == 0
    # resume from a mid-run checkpoint reproduces the uninterrupted tail
    import shutil

    kept = sorted(int(p.name) for p in (tmp_path / "ckpt_k3").iterdir()
                  if p.name.isdigit())
    resume_step = kept[0]
    assert resume_step < max(s for _n, _v, s in seq0)
    for s in kept[1:]:
        shutil.rmtree(tmp_path / "ckpt_k3" / str(s))
    s3r, dir3r = run(3, "_k3", resume=True)
    np.testing.assert_array_equal(np.asarray(s0.state.params_vec),
                                  np.asarray(s3r.state.params_vec))
    drop = ("comm/",)  # process-local cumulative ledger, by design
    tail = [r for r in _scalar_sequence(dir3r)
            if r[2] >= resume_step and not r[0].startswith(drop)]
    want = [r for r in seq0 if r[2] >= resume_step
            and not r[0].startswith(drop)]
    assert tail == want, "scan resume diverged from the uninterrupted run"


# ---------------------------------------------------------------------------
# refusals: what a scanned block cannot honor is named at construction
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kw,needle", [
    (dict(device_data=False), "device-resident"),
    (dict(control_policy="fixed", control_schedule="0-=0",
          ladder="k=40,20"), "control"),
    (dict(pipeline_depth=2), "pipeline_depth"),
    (dict(preempt_signals=True), "preempt"),
    (dict(chaos="preempt@3"), "preempt"),
    (dict(fsdp=True), "index path"),
])
def test_scan_rounds_incompatible_knobs_refused(kw, needle):
    base = dict(BASE, mode="sketch", error_type="virtual", k=40,
                num_rows=3, num_cols=256, topk_method="threshold",
                scan_rounds=4, telemetry_level=1)
    base.update(kw)
    with pytest.raises(ValueError, match=needle):
        Config(**base)


def test_scan_engine_refuses_session_without_device_data():
    cfg = _cfg(scan_rounds=3)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)  # nothing attached
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    with pytest.raises(ValueError, match="device-resident"):
        ScanRounds(cfg, sess, sampler, _lr_fn, num_rounds=5,
                   steps_per_epoch=5)
