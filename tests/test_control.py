"""Adaptive communication-budget controller (control/) tests.

Covers the ladder/schedule grammars, the three policies (incl. the
hysteresis no-oscillation property and the budget-exhaustion clamp),
per-backend ``Compressor.migrate_state`` semantics, zero-retrace rung
switching on the real 8-device session, the per-rung ledger exactness
invariant (full participation AND fedsim dropout masking, validated by
the REAL schema checker), checkpoint carry of controller state across
rung-shape-changing ladders, and the control-off bit-compat guarantees
(the golden parity recordings in test_compress_parity are the other half
of that pin). The cv_train e2e acceptance run (3-rung ef_feedback ladder:
>= 1 switch, xla/retraces == 0, resume reproduces the rung sequence)
lives at the bottom.
"""

import importlib.util
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest
from test_round import BASE, _setup

from commefficient_tpu.control import (
    BudgetExhaustedError,
    build_controller,
    controller_header,
    ladder_configs,
    parse_ladder,
    parse_schedule,
    validate_rung_costs,
)
from commefficient_tpu.control.policy import DecisionContext, EfFeedbackPolicy
from commefficient_tpu.data import FedSampler
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.utils.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(REPO, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# grammars
# ---------------------------------------------------------------------------

def test_ladder_grammar_parses():
    assert parse_ladder("") == ()
    assert parse_ladder("k=60000,30000,10000") == (
        {"k": 60000}, {"k": 30000}, {"k": 10000},
    )
    assert parse_ladder(" k=50, 25 ; num_cols = 500, 250 ") == (
        {"k": 50, "num_cols": 500}, {"k": 25, "num_cols": 250},
    )


@pytest.mark.parametrize("bad", [
    "k",                      # no values
    "k=",                     # empty values
    "k=a,b",                  # non-int
    "bogus=1,2",              # unknown field
    "k=1,2;k=3,4",            # duplicate field
    "k=10,5;num_cols=100",    # mismatched lengths
    "k=0,5",                  # < 1
])
def test_ladder_grammar_rejects(bad):
    with pytest.raises(ValueError, match="Grammar"):
        parse_ladder(bad)


def test_ladder_configs_resolve_rung_overrides():
    cfg = Config(mode="powersgd", error_type="virtual",
                 control_policy="fixed", control_schedule="0-=0",
                 ladder="powersgd_rank=4,2")
    c0, c1 = ladder_configs(cfg)
    assert (c0.powersgd_rank, c1.powersgd_rank) == (4, 2)
    cfg = Config(mode="sketch", error_type="virtual", topk_method="threshold",
                 telemetry_level=1, control_policy="ef_feedback",
                 ladder="num_cols=512,256", num_rows=3, k=40)
    c0, c1 = ladder_configs(cfg)
    assert (c0.num_cols, c1.num_cols) == (512, 256)


def test_rung_cost_ordering_enforced():
    validate_rung_costs([
        {"upload_bytes": 100, "download_bytes": 10},
        {"upload_bytes": 100, "download_bytes": 10},  # tie is legal
        {"upload_bytes": 50, "download_bytes": 10},
    ])
    with pytest.raises(ValueError, match="MORE than"):
        validate_rung_costs([
            {"upload_bytes": 50, "download_bytes": 10},
            {"upload_bytes": 100, "download_bytes": 10},
        ])


def test_schedule_grammar():
    assert parse_schedule("") == ()
    assert parse_schedule("0-99=2,100-199=1,200-=0") == (
        (0, 99, 2), (100, 199, 1), (200, None, 0),
    )
    assert parse_schedule("5=1") == ((5, 5, 1),)
    for bad in ("abc", "0-99", "99-0=1", "0-5=1,3-9=0", "0-=1,50-=0"):
        with pytest.raises(ValueError, match="Grammar"):
            parse_schedule(bad)


# ---------------------------------------------------------------------------
# config validation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kw,msg", [
    (dict(ladder="k=10,5"), "ladder without a controller"),
    (dict(control_policy="ef_feedback", telemetry_level=1), ">= 2"),
    (dict(control_policy="ef_feedback", ladder="k=10,5"),
     "telemetry_level"),
    (dict(control_policy="budget_pacing"), "budget_mb"),
    (dict(budget_mb=1.0), "control_policy='budget_pacing'"),
    (dict(control_policy="fixed"), "control_schedule"),
    (dict(control_policy="budget_pacing", budget_mb=1.0,
          control_schedule="0-=0"), "fixed"),
    (dict(control_policy="fixed", control_schedule="0-=3",
          ladder="k=10,5"), "rung 3"),
    (dict(control_policy="fixed", control_schedule="0-=0",
          ladder="num_cols=100,50"), "num_cols has no effect"),
    (dict(mode="uncompressed", control_policy="fixed",
          control_schedule="0-=0", ladder="k=10,5"), "k has no effect"),
    (dict(control_policy="ef_feedback", ladder="k=10,5",
          telemetry_level=1, control_ef_up=0.0, control_ef_down=0.0),
     "dead band"),
    (dict(control_policy="ef_feedback", ladder="k=10,5",
          telemetry_level=1, control_hysteresis=0), "hysteresis"),
])
def test_config_rejects_inconsistent_control(kw, msg):
    base = dict(mode="true_topk", error_type="virtual")
    base.update(kw)
    with pytest.raises(ValueError, match=msg):
        Config(**base)


def test_config_accepts_budget_only_controller():
    # budget_pacing without a ladder = single implicit rung, pure hard cap
    cfg = Config(mode="true_topk", error_type="virtual",
                 control_policy="budget_pacing", budget_mb=1.0)
    assert cfg.control_enabled
    assert ladder_configs(cfg) == (cfg,)


def test_ladder_field_powersgd_rank_requires_powersgd():
    with pytest.raises(ValueError, match="powersgd_rank has no effect"):
        Config(mode="sketch", error_type="virtual",
               control_policy="fixed", control_schedule="0-=0",
               ladder="powersgd_rank=4,2")


# ---------------------------------------------------------------------------
# policy units
# ---------------------------------------------------------------------------

def _ctx(step, rung, num_rungs=3, *, spent=0, budget=None, last_switch=-1,
         hysteresis=1, bytes_fn=None, num_rounds=100):
    return DecisionContext(
        step=step, num_rounds=num_rounds, rung=rung, num_rungs=num_rungs,
        round_bytes=bytes_fn or (lambda r: [300, 200, 100][r]),
        spent_bytes=spent, budget_bytes=budget, last_switch_round=last_switch,
        hysteresis=hysteresis,
    )


def test_budget_pacing_picks_most_expensive_affordable():
    from commefficient_tpu.control.policy import BudgetPacingPolicy

    cfg = Config(mode="true_topk", error_type="virtual",
                 control_policy="budget_pacing", budget_mb=1.0)
    p = BudgetPacingPolicy(cfg)
    # allowance 3000/10 = 300 -> rung 0 affordable
    assert p.decide(_ctx(0, 0, budget=3000, num_rounds=10)) == 0
    # allowance (3000-2400)/5=120 -> only rung 2 fits
    assert p.decide(_ctx(5, 0, spent=2400, budget=3000, num_rounds=10)) == 2
    # nothing fits the allowance -> cheapest (the controller clamp owns
    # the hard stop)
    assert p.decide(_ctx(9, 2, spent=2990, budget=3000, num_rounds=10)) == 2


def test_ef_feedback_decisions_and_hysteresis():
    cfg = Config(mode="true_topk", error_type="virtual", telemetry_level=1,
                 control_policy="ef_feedback", ladder="k=30,20,10",
                 control_ef_up=0.10, control_ef_down=-0.05,
                 control_hysteresis=4)
    p = EfFeedbackPolicy(cfg)
    assert p.initial_rung(3) == 2  # starts cheapest
    # no telemetry yet -> hold
    assert p.decide(_ctx(0, 2, hysteresis=4)) == 2
    p.observe(0, {"diag/ef_residual_norm": 1.0})
    p.observe(1, {"diag/ef_residual_norm": 1.5})  # slope 0.5 > up
    assert p.decide(_ctx(2, 2, hysteresis=4)) == 1
    # inside the hysteresis window the signal is ignored
    assert p.decide(_ctx(3, 1, last_switch=2, hysteresis=4)) == 1
    # shrinking bank -> step cheaper once the window passes
    p.observe(2, {"diag/ef_residual_norm": 1.2})  # slope -0.2 < down
    assert p.decide(_ctx(6, 1, last_switch=2, hysteresis=4)) == 2
    # climbs are clamped at rung 0
    p.observe(3, {"diag/ef_residual_norm": 9.9})
    assert p.decide(_ctx(10, 0, last_switch=2, hysteresis=4)) == 0


def test_ef_feedback_no_oscillation_property():
    """Adversarial alternating signal: the switch count over N rounds is
    bounded by N / hysteresis (+1), never one-per-round flapping."""
    H = 5
    cfg = Config(mode="true_topk", error_type="virtual", telemetry_level=1,
                 control_policy="ef_feedback", ladder="k=30,20,10",
                 control_ef_up=0.05, control_ef_down=-0.05,
                 control_hysteresis=H)
    p = EfFeedbackPolicy(cfg)
    rung, last_switch, switches = 1, -1, 0
    ef = 1.0
    N = 40
    for step in range(N):
        # alternate violent growth/collapse — both thresholds crossed
        # every single round
        ef = ef * (3.0 if step % 2 == 0 else 0.2)
        p.observe(step, {"diag/ef_residual_norm": ef})
        nxt = p.decide(_ctx(step, rung, last_switch=last_switch,
                            hysteresis=H))
        if nxt != rung:
            switches += 1
            last_switch = step
            rung = nxt
    assert switches <= N // H + 1, (
        f"{switches} switches in {N} rounds under hysteresis {H}"
    )


def test_fidelity_trigger_climbs():
    cfg = Config(mode="true_topk", error_type="virtual", telemetry_level=2,
                 control_policy="ef_feedback", ladder="k=30,20,10",
                 control_fidelity_max=0.5, control_hysteresis=1)
    p = EfFeedbackPolicy(cfg)
    p.observe(0, {"diag/sketch_est_rel_err": 0.9})  # worse than max
    assert p.decide(_ctx(1, 2)) == 1


# ---------------------------------------------------------------------------
# migrate_state per backend
# ---------------------------------------------------------------------------

def test_migrate_dense_k_change_is_identity():
    from commefficient_tpu.compress import get_compressor

    cfg = Config(mode="true_topk", error_type="virtual",
                 virtual_momentum=0.9, k=40)
    old = get_compressor(cfg, d=200)
    new = get_compressor(cfg.replace(k=10), d=200)
    m = jnp.arange(200.0)
    e = jnp.arange(200.0) * 2
    m2, e2, x2 = old.migrate_state(new, m, e, ())
    assert m2 is m and e2 is e and x2 == ()


def test_migrate_sketch_k_change_is_identity():
    from commefficient_tpu.compress import get_compressor
    from commefficient_tpu.ops.countsketch import CountSketch

    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=40, num_rows=3, num_cols=256)
    spec = CountSketch(d=500, c=256, r=3, seed=1)
    old = get_compressor(cfg, d=500, spec=spec)
    new = get_compressor(cfg.replace(k=10), d=500, spec=spec)
    t = jnp.ones(spec.table_shape)
    m2, e2, _ = old.migrate_state(new, t, t, ())
    assert m2 is t and e2 is t


def test_migrate_sketch_num_cols_resketches_heavy_hitters():
    """A num_cols switch re-sketches the decodable top-k mass: a k-sparse
    signal sketched into the old table must round-trip through migration
    and estimate correctly from the NEW table."""
    from commefficient_tpu.compress import get_compressor
    from commefficient_tpu.ops.countsketch import (
        CountSketch,
        estimate_at,
        sketch_vec,
    )

    d, k = 4000, 8
    cfg = Config(mode="sketch", error_type="virtual", virtual_momentum=0.9,
                 k=k, num_rows=5, num_cols=1024)
    spec_old = CountSketch(d=d, c=1024, r=5, seed=3)
    spec_new = CountSketch(d=d, c=512, r=5, seed=3)
    old = get_compressor(cfg, d=d, spec=spec_old)
    new = get_compressor(cfg.replace(num_cols=512), d=d, spec=spec_new)
    rng = np.random.default_rng(0)
    idx = rng.choice(d, size=k, replace=False)
    vec = np.zeros(d, np.float32)
    vec[idx] = rng.normal(size=k).astype(np.float32) * 10 + 20
    table = sketch_vec(spec_old, jnp.asarray(vec))
    m2, e2, _ = old.migrate_state(new, table, table, ())
    assert m2.shape == spec_new.table_shape
    est = np.asarray(estimate_at(spec_new, e2, jnp.asarray(idx)))
    np.testing.assert_allclose(est, vec[idx], rtol=0.2, atol=1.0)


def test_migrate_powersgd_rank_pad_truncate():
    from commefficient_tpu.compress import get_compressor

    cfg = Config(mode="powersgd", error_type="virtual", powersgd_rank=4)
    d = 400
    old = get_compressor(cfg, d=d)
    q = old.init_extra_state()
    m = jnp.zeros(d)
    e = jnp.zeros(d)
    # truncate 4 -> 2: first columns retained exactly
    new2 = get_compressor(cfg.replace(powersgd_rank=2), d=d)
    _, _, q2 = old.migrate_state(new2, m, e, q)
    assert q2.shape == (old.m, 2)
    np.testing.assert_array_equal(np.asarray(q2), np.asarray(q[:, :2]))
    # pad 2 -> 4: old columns retained, fresh seed-derived tail
    _, _, q4 = new2.migrate_state(old, m, e, q2)
    assert q4.shape == (old.m, 4)
    np.testing.assert_array_equal(np.asarray(q4[:, :2]), np.asarray(q2))
    assert np.any(np.asarray(q4[:, 2:]) != 0)
    # no warm start carries nothing
    cold = get_compressor(cfg.replace(powersgd_warm_start=False), d=d)
    cold2 = get_compressor(
        cfg.replace(powersgd_warm_start=False, powersgd_rank=2), d=d
    )
    assert cold.migrate_state(cold2, m, e, ())[2] == ()


# ---------------------------------------------------------------------------
# controller + real session
# ---------------------------------------------------------------------------

_LADDER_BASE = dict(
    mode="local_topk", error_type="local", topk_method="threshold",
    telemetry_level=1, control_policy="fixed",
    control_schedule="0-1=0,2-3=1,4-=2", ladder="k=60,30,15",
)


def _ladder_session(**kw):
    cfg = Config(**{**BASE, **_LADDER_BASE, **kw})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    return cfg, sess, sampler


def _drive(cfg, sess, sampler, n_rounds, writer, tmp_path):
    from commefficient_tpu.telemetry import build_telemetry_riders
    from commefficient_tpu.utils.logging import drain_round_metrics

    ctrl = build_controller(cfg, sess, num_rounds=n_rounds)
    ctrl.prewarm(sampler, 0.2)
    ledger, flight = build_telemetry_riders(cfg, sess, writer)
    pending = []
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, 0.2)
        pending.append((r, 0.2, m))
    drain_round_metrics(pending, writer, lambda *a: None, ledger=ledger,
                        flight=flight, controller=ctrl)
    return ctrl, ledger, flight


def test_fixed_schedule_switches_and_zero_retraces(tmp_path):
    from commefficient_tpu.utils.logging import MetricsWriter

    cfg, sess, sampler = _ladder_session()
    writer = MetricsWriter(str(tmp_path / "run"), cfg=cfg,
                           extra_header=controller_header(sess))
    ctrl, ledger, _ = _drive(cfg, sess, sampler, 6, writer, tmp_path)
    writer.close()
    assert ctrl.switches == 2
    assert sess.retrace_sentinel.retraces == 0
    assert sess.active_rung == 2
    # per-rung ledger accounting: 2 rounds at each rung's own byte rate
    s = ledger.summary()
    assert [r["rounds"] for r in s["rungs"]] == [2, 2, 2]
    # per-client-link units (unmasked ledger): 2k floats x 4 B per rung
    want_up = 2 * (2 * 60 * 4) + 2 * (2 * 30 * 4) + 2 * (2 * 15 * 4)
    assert s["cum_up_bytes"] == want_up
    # the real checker enforces the v4 per-rung invariant
    ledger.write(str(tmp_path / "run"))
    mod = _checker()
    rec = mod.validate_comm_ledger(str(tmp_path / "run" / "comm_ledger.json"))
    assert [r["rounds"] for r in rec["rungs"]] == [2, 2, 2]
    # metrics.jsonl validates too (control/ scalars under the v4 schema),
    # and the run header carries the controller block
    mod.validate_metrics_jsonl(str(tmp_path / "run" / "metrics.jsonl"))
    with open(tmp_path / "run" / "metrics.jsonl") as f:
        header = json.loads(f.readline())
    assert header["controller"]["policy"] == "fixed"
    assert header["controller"]["num_rungs"] == 3


def test_checker_rejects_tampered_rung_rounds(tmp_path):
    from commefficient_tpu.utils.logging import MetricsWriter

    cfg, sess, sampler = _ladder_session()
    writer = MetricsWriter(str(tmp_path / "run"), cfg=cfg)
    _, ledger, _ = _drive(cfg, sess, sampler, 6, writer, tmp_path)
    writer.close()
    path = ledger.write(str(tmp_path / "run"))
    with open(path) as f:
        rec = json.load(f)
    rec["rungs"][0]["rounds"] += 1
    with open(path, "w") as f:
        json.dump(rec, f)
    mod = _checker()
    with pytest.raises(mod.SchemaError, match="rounds sum"):
        mod.validate_comm_ledger(path)


def test_ladder_ledger_exact_under_fedsim_masking(tmp_path):
    """The satellite invariant: cumulative bytes == sum over rounds of the
    ACTIVE rung's bytes, exact under dropout masking — per-rung live
    counts recovered from the same drained scalars the run logged."""
    from commefficient_tpu.utils.logging import MetricsWriter

    cfg, sess, sampler = _ladder_session(
        availability="bernoulli", dropout_prob=0.4, fuse_clients=False,
    )
    writer = MetricsWriter(str(tmp_path / "run"), cfg=cfg)
    ctrl, ledger, _ = _drive(cfg, sess, sampler, 6, writer, tmp_path)
    writer.close()
    s = ledger.summary()
    # recompute the invariant from the logged per-rung live counts
    want_up = sum(
        r["live_client_rounds"] * r["bytes_per_round"]["upload_bytes"]
        for r in s["rungs"]
    )
    assert s["cum_up_bytes"] == want_up
    assert s["live_client_rounds"] == sum(
        r["live_client_rounds"] for r in s["rungs"]
    )
    # some round actually dropped clients, else the test is vacuous
    assert s["live_client_rounds"] < 6 * cfg.num_workers
    # the controller's own budget view agrees with the ledger exactly
    assert ctrl.spent_up == s["cum_up_bytes"]
    assert ctrl.spent_down == s["cum_down_bytes"]
    ledger.write(str(tmp_path / "run"))
    _checker().validate_comm_ledger(
        str(tmp_path / "run" / "comm_ledger.json")
    )


def test_budget_clamp_demotes_then_exhausts(tmp_path):
    """The hard cap: the controller demotes to cheaper rungs when the
    decided rung would cross the budget, and raises BudgetExhaustedError
    BEFORE the round that even the cheapest rung cannot pay for."""
    # per-round bytes (TinyMLP d=212, W=8 irrelevant — per-client units):
    # rung0 2*60*4+848=1328, rung1 1088, rung2 968
    cfg, sess, sampler = _ladder_session(
        control_schedule="0-=0", budget_mb=0.005,  # 5000 B
    )
    ctrl = build_controller(cfg, sess, num_rounds=10)
    rungs_used = []
    with pytest.raises(BudgetExhaustedError) as ei:
        for r in range(10):
            ids, batch = sampler.sample_round(r)
            m = sess.train_round(ids, batch, 0.2)
            rungs_used.append(int(float(np.asarray(m["control/rung"]))))
    assert rungs_used == [0, 0, 0, 2]  # demoted at round 3, stopped at 4
    assert ctrl.spent_bytes <= 5000  # the cap was never crossed
    assert ei.value.step == 4
    assert "completed 4 full rounds" in str(ei.value)


def test_budget_remaining_scalar_rides_metrics():
    cfg, sess, sampler = _ladder_session(
        control_policy="budget_pacing", control_schedule="",
        budget_mb=1.0,
    )
    build_controller(cfg, sess, num_rounds=4)
    ids, batch = sampler.sample_round(0)
    m = sess.train_round(ids, batch, 0.2)
    assert m["control/budget_remaining_bytes"] == 1_000_000 - 1328
    assert m["control/rung"] == 0.0  # rich budget -> most expensive rung


@pytest.mark.slow  # r20 tier budget (~9 s of sketch compiles): the
# num_cols migration algebra stays tier-1 in the resketch unit test and
# the switch/zero-retrace mechanics in the fixed-schedule e2e
def test_num_cols_ladder_switches_table_shapes():
    """A geometry-changing ladder: the switch migrates the sketch tables
    to the new rung's layout and training stays finite — and the switch
    itself causes no retrace (both rungs were prewarmed)."""
    cfg = Config(**{**BASE, **dict(
        mode="sketch", error_type="virtual", virtual_momentum=0.9,
        k=40, num_rows=3, num_cols=512, topk_method="threshold",
        telemetry_level=1, control_policy="fixed",
        control_schedule="0-1=0,2-=1", ladder="num_cols=512,256",
    )})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ctrl = build_controller(cfg, sess, num_rounds=4)
    ctrl.prewarm(sampler, 0.2)
    shapes = []
    for r in range(4):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, 0.2)
        assert np.isfinite(float(np.asarray(m["loss"])))
        shapes.append(tuple(sess.state.error.shape))
    assert shapes[1] != shapes[2], "table layout must change at the switch"
    assert ctrl.switches == 1
    assert sess.retrace_sentinel.retraces == 0


def test_fsdp_ladder_switch_trains_and_accounts():
    """The FSDP engine under a k-ladder: per-rung fsdp round programs,
    identity state migration over the sharded [Dp] banks, zero retraces
    across the switch, and the same per-rung controller accounting."""
    cfg = Config(**{**BASE, **dict(
        mode="true_topk", error_type="virtual", virtual_momentum=0.9,
        fsdp=True, topk_method="threshold", telemetry_level=1,
        control_policy="fixed", control_schedule="0-1=0,2-=1",
        ladder="k=40,20",
    )})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ctrl = build_controller(cfg, sess, num_rounds=4)
    ctrl.prewarm(sampler, 0.2)
    for r in range(4):
        ids, batch = sampler.sample_round(r)
        m = sess.train_round(ids, batch, 0.2)
        assert np.isfinite(float(np.asarray(m["loss"])))
    assert ctrl.switches == 1
    assert sess.active_rung == 1
    assert sess.retrace_sentinel.retraces == 0
    # sharded [Dp] server banks carried across the switch untouched
    # (identity migration) and per-rung rounds accounted
    assert ctrl.rounds_seen == 4


def test_control_none_builds_nothing():
    cfg = Config(**{**BASE, "mode": "true_topk", "error_type": "virtual",
                    "k": 40})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    assert len(sess.rungs) == 1 and sess.rungs[0].label == ""
    assert sess.controller is None
    assert controller_header(sess) == {}
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ids, batch = sampler.sample_round(0)
    m = sess.train_round(ids, batch, 0.2)
    assert not any(k.startswith("control/") for k in m)


# ---------------------------------------------------------------------------
# checkpoint carry
# ---------------------------------------------------------------------------

def test_controller_state_checkpoint_roundtrip(tmp_path):
    """Save at a non-initial rung of a GEOMETRY-CHANGING ladder; a fresh
    session+controller restores the exact rung, policy state, and byte
    spend — the template-retry walk finds the saved rung's state layout."""
    from commefficient_tpu.utils.checkpoint import FedCheckpointer

    kw = dict(
        mode="sketch", error_type="virtual", virtual_momentum=0.9,
        k=40, num_rows=3, num_cols=512, topk_method="threshold",
        telemetry_level=1, control_policy="fixed",
        control_schedule="0-1=0,2-=1", ladder="num_cols=512,256",
        checkpoint_dir=str(tmp_path / "ckpt"), checkpoint_every=3,
    )
    cfg = Config(**{**BASE, **kw})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ctrl = build_controller(cfg, sess, num_rounds=6)
    ctrl.prewarm(sampler, 0.2)
    ckpt = FedCheckpointer(cfg)
    for r in range(4):
        ids, batch = sampler.sample_round(r)
        sess.train_round(ids, batch, 0.2)
    assert sess.active_rung == 1  # switched at round 2
    ckpt.maybe_save(sess, 4, force=True)
    saved_err = np.asarray(sess.state.error)
    saved_spent = ctrl.spent_bytes

    sess2 = FederatedSession(cfg, params, loss_fn)
    ctrl2 = build_controller(cfg, sess2, num_rounds=6)
    assert sess2.active_rung == 0  # fresh session starts per schedule
    step = ckpt.restore(sess2)
    ckpt.close()
    assert step == 4
    assert sess2.active_rung == 1
    assert ctrl2.switches == 1 and ctrl2.rounds_seen == 4
    assert ctrl2.spent_bytes == saved_spent
    np.testing.assert_array_equal(np.asarray(sess2.state.error), saved_err)
    # the resumed controller continues the same sequence
    ids, batch = sampler.sample_round(4)
    m = sess2.train_round(ids, batch, 0.2)
    assert float(np.asarray(m["control/rung"])) == 1.0


# ---------------------------------------------------------------------------
# cv_train e2e (the PR acceptance run)
# ---------------------------------------------------------------------------

def _rung_sequence(logdir):
    """{step: rung} from every metrics.jsonl under ``logdir``."""
    out = {}
    for root, _, files in os.walk(logdir):
        for f in files:
            if f != "metrics.jsonl":
                continue
            with open(os.path.join(root, f)) as fh:
                for line in fh:
                    rec = json.loads(line)
                    if rec.get("name") == "control/rung":
                        out[rec["step"]] = rec["value"]
    return out


def _scalar_trail(logdir, name):
    out = {}
    for root, _, files in os.walk(logdir):
        for f in files:
            if f != "metrics.jsonl":
                continue
            with open(os.path.join(root, f)) as fh:
                for line in fh:
                    rec = json.loads(line)
                    if rec.get("name") == name:
                        out[rec["step"]] = rec["value"]
    return out


@pytest.mark.slow  # ~27 s of femnist compiles; the clamp/exhaustion logic
# and the v4 ledger/flight blocks hold default-tier coverage in the unit
# tests above — this is the full-entry artifact check, kept for local runs
def test_cv_train_budget_hard_stop_e2e(tmp_path):
    """budget_pacing with no ladder = a pure byte cap: cv_train hard-stops
    with BudgetExhaustedError BEFORE the unaffordable round, the ledger is
    still written (within budget, v4-valid), and the crash flight dump
    carries the controller block."""
    from commefficient_tpu.train.cv_train import main as cv_main

    logdir = tmp_path / "runs"
    with pytest.raises(BudgetExhaustedError) as ei:
        cv_main(
            [],
            dataset_name="femnist",
            model="resnet9",
            mode="true_topk",
            error_type="virtual",
            topk_method="threshold",
            k=2000,
            num_clients=6,
            num_workers=4,
            num_devices=4,
            local_batch_size=32,
            num_epochs=1,
            pivot_epoch=1,
            lr_scale=0.1,
            dataset_dir=str(tmp_path),
            logdir=str(logdir),
            seed=0,
            telemetry_level=1,
            perf_audit=False,
            control_policy="budget_pacing",
            # true_topk: up = down = D*4 B ~ 26.6 MB each per round ->
            # ~53 MB/round; 160 MB admits 3 full rounds, not 4
            budget_mb=160.0,
        )
    assert ei.value.step == 3
    run_dir = next(p for p in logdir.iterdir() if p.is_dir())
    mod = _checker()
    ledger = mod.validate_comm_ledger(run_dir / "comm_ledger.json")
    assert ledger["rounds"] == 3  # only the affordable rounds were billed
    assert ledger["cum_bytes"] <= 160_000_000
    flights = list(run_dir.glob("flight_*.json"))
    assert flights, "the hard stop must dump a flight record"
    rec = mod.validate_flight(flights[0])
    assert rec["controller"]["policy"] == "budget_pacing"


@pytest.mark.slow  # ~130 s of femnist compiles — moved to the slow tier
# in the sketch-gap PR per the 870 s tier-1 budget (the PR-9/10
# precedent). Its claims hold default-tier coverage at TinyMLP scale:
# test_pipeline.py::test_runner_pipelined_resume_bit_exact_tinymlp runs
# the SAME 3-rung ef_feedback ladder through the REAL shared runner
# (>= 1 switch, zero retraces, mid-run checkpoint resume reproducing the
# tail), and the session-level switch/checkpoint/ledger pins above cover
# the controller mechanics.
def test_cv_train_ladder_ef_feedback_e2e_with_resume(tmp_path):
    """Acceptance: a cv_train e2e run with a 3-rung ladder under
    ef_feedback performs >= 1 rung switch with ZERO RetraceSentinel fires,
    and a checkpoint resume reproduces the identical rung sequence."""
    from commefficient_tpu.train.cv_train import main as cv_main

    kw = dict(
        dataset_name="femnist",
        model="resnet9",
        mode="true_topk",
        error_type="virtual",
        virtual_momentum=0.9,
        topk_method="threshold",
        num_clients=6,
        num_workers=4,
        num_devices=4,
        local_batch_size=32,  # 5 rounds/epoch on the femnist stand-in
        pivot_epoch=1,
        lr_scale=0.1,
        dataset_dir=str(tmp_path),
        seed=0,
        telemetry_level=1,
        perf_audit=False,  # the AOT audit is test_xla_audit's territory
        control_policy="ef_feedback",
        ladder="k=4000,2000,1000",
        # force deterministic climbing: any EF growth at all climbs, and
        # the EF bank grows from zero in the first rounds by construction
        control_ef_up=1e-9,
        control_ef_down=-1.0,
        control_hysteresis=1,
        checkpoint_dir=str(tmp_path / "ckpt"),
        checkpoint_every=3,  # mid-epoch drains -> mid-epoch decisions
    )
    # run C: 2 epochs uninterrupted, checkpointing every 3 rounds
    cv_main([], num_epochs=2, logdir=str(tmp_path / "runC"), **kw)
    seq_c = _rung_sequence(tmp_path / "runC")
    assert seq_c[0] == 2.0, "ef_feedback starts at the cheapest rung"
    switches = sum(
        1 for s in range(1, 10) if seq_c[s] != seq_c[s - 1]
    )
    assert switches >= 1, f"no rung switch in {seq_c}"
    retraces = _scalar_trail(tmp_path / "runC", "xla/retraces")
    assert set(retraces.values()) == {0.0}, (
        f"rung switches caused retraces: {retraces}"
    )
    # run B: resume from run C's own MID-RUN checkpoint (drop the later
    # steps so restore picks the round-6 one — a kill at round 6) and
    # replay rounds 6-9; the resumed rung sequence must be bit-identical
    # to the uninterrupted run's (controller blob + drained-state carry)
    kept = sorted(int(p.name) for p in (tmp_path / "ckpt").iterdir()
                  if p.name.isdigit())
    resume_step = kept[0]
    assert resume_step < 10, f"no mid-run checkpoint survived: {kept}"
    for s in kept[1:]:
        import shutil

        shutil.rmtree(tmp_path / "ckpt" / str(s))
    cv_main([], num_epochs=2, logdir=str(tmp_path / "runB"), resume=True,
            **kw)
    seq_b = _rung_sequence(tmp_path / "runB")
    resumed = {s: v for s, v in seq_b.items() if s >= resume_step}
    assert resumed == {s: v for s, v in seq_c.items()
                       if s >= resume_step}, (
        f"resume diverged from the uninterrupted rung sequence: "
        f"B={seq_b} C={seq_c}"
    )
    assert set(_scalar_trail(tmp_path / "runB", "xla/retraces").values()) \
        == {0.0}


# ---------------------------------------------------------------------------
# staleness_aware (elastic-fleet PR): rung walk on the async staleness
# band + live (K, C) retunes through the controller -> engine listener
# ---------------------------------------------------------------------------

_SA_KW = dict(mode="true_topk", error_type="virtual", telemetry_level=1,
              control_policy="staleness_aware", ladder="k=30,20,10",
              async_buffer=4, async_concurrency=2)


def _sa_ctx(step, rung, *, stale=None, fill=None, workers=8,
            last_switch=-1, hysteresis=1):
    return DecisionContext(
        step=step, num_rounds=100, rung=rung, num_rungs=3,
        round_bytes=lambda r: [300, 200, 100][r], spent_bytes=0,
        budget_bytes=None, last_switch_round=last_switch,
        hysteresis=hysteresis, staleness_mean=stale, buffer_fill=fill,
        num_workers=workers,
    )


@pytest.mark.parametrize("kw,msg", [
    ({**_SA_KW, "async_buffer": 0}, "async_buffer"),
    ({**_SA_KW, "ladder": "k=30"}, ">= 2"),
    ({**_SA_KW, "telemetry_level": 0}, "telemetry_level"),
    ({**_SA_KW, "control_staleness_hi": 0.4,
      "control_staleness_lo": 0.5}, "must exceed control_staleness_lo"),
    ({**_SA_KW, "control_fill_hi": 0.2, "control_fill_lo": 0.25},
     "control_fill"),
])
def test_config_rejects_inconsistent_staleness_aware(kw, msg):
    with pytest.raises(ValueError, match=msg):
        Config(**kw)


def test_staleness_aware_walk_band_and_hysteresis():
    from commefficient_tpu.control.policy import (
        ControlPolicy,
        StalenessAwarePolicy,
    )

    # the ADAPTS_ASYNC capability is what gates the retune plumbing and
    # the control/async_* scalars — a class attr, not a name match
    assert not ControlPolicy.ADAPTS_ASYNC
    assert StalenessAwarePolicy.ADAPTS_ASYNC
    p = StalenessAwarePolicy(Config(**_SA_KW))
    assert p.decide(_sa_ctx(0, 1)) == 1  # synchronous round: hold
    assert p.decide(_sa_ctx(0, 1, stale=3.0)) == 2    # over band: cheaper
    assert p.decide(_sa_ctx(0, 2, stale=3.0)) == 2    # clamped at last
    assert p.decide(_sa_ctx(0, 1, stale=0.1)) == 0    # under: fidelity
    assert p.decide(_sa_ctx(0, 0, stale=0.1)) == 0    # clamped at 0
    assert p.decide(_sa_ctx(0, 1, stale=1.0)) == 1    # inside band: hold
    # inside the hysteresis window the signal is ignored
    assert p.decide(_sa_ctx(3, 1, stale=9.0, last_switch=2,
                            hysteresis=4)) == 1


def test_staleness_aware_no_oscillation_property():
    """Adversarial alternating staleness (far over / far under the band
    every update): switches over N updates stay bounded by
    N / hysteresis (+1) — the ef_feedback anti-flap property."""
    from commefficient_tpu.control.policy import StalenessAwarePolicy

    H = 5
    p = StalenessAwarePolicy(Config(**_SA_KW, control_hysteresis=H))
    rung, last_switch, switches = 1, -1, 0
    N = 40
    for step in range(N):
        stale = 9.0 if step % 2 == 0 else 0.0
        nxt = p.decide(_sa_ctx(step, rung, stale=stale,
                               last_switch=last_switch, hysteresis=H))
        if nxt != rung:
            switches += 1
            last_switch = step
            rung = nxt
    assert switches <= N // H + 1, (
        f"{switches} switches in {N} updates under hysteresis {H}"
    )


def test_staleness_aware_retune_moves():
    """decide_async is one move per decision toward the fill band:
    backlog over the band grows K; hot staleness sheds concurrency to 1,
    then shrinks K only while ALSO starved; a fresh fleet restores C up
    to the configured ceiling; in-band (or signal-less) holds."""
    from commefficient_tpu.control.policy import StalenessAwarePolicy

    p = StalenessAwarePolicy(Config(**_SA_KW))
    assert p.decide_async(_sa_ctx(0, 0, stale=1.0, fill=8), 4, 2) == (5, 2)
    assert p.decide_async(_sa_ctx(0, 0, stale=3.0, fill=2), 4, 2) == (4, 1)
    assert p.decide_async(_sa_ctx(0, 0, stale=3.0, fill=0), 4, 1) == (3, 1)
    # stale but neither concurrency to shed nor starvation: hold
    assert p.decide_async(_sa_ctx(0, 0, stale=3.0, fill=3), 4, 1) == (4, 1)
    assert p.decide_async(_sa_ctx(0, 0, stale=0.1, fill=2), 4, 1) == (4, 2)
    assert p.decide_async(_sa_ctx(0, 0, stale=0.1, fill=2), 4, 2) == (4, 2)
    assert p.decide_async(_sa_ctx(0, 0, stale=1.0, fill=2), 4, 2) == (4, 2)
    assert p.decide_async(_sa_ctx(0, 0), 4, 2) == (4, 2)  # sync round
    # backlog over the band but K already at the fleet width: hold, the
    # buffer cannot absorb more than one contribution per live worker
    assert p.decide_async(_sa_ctx(0, 0, stale=1.0, fill=20, workers=4),
                          4, 2) == (4, 2)


def test_fixed_policy_async_run_emits_no_retune_scalars():
    """Capability gating: an asyncfed run under a NON-adaptive policy
    must not grow control/async_* keys (nor register retune listeners) —
    its sync/async scalar sets stay comparable run-to-run."""
    from commefficient_tpu.asyncfed import AsyncFederation

    cfg = Config(mode="true_topk", error_type="virtual", telemetry_level=1,
                 control_policy="fixed", control_schedule="0-=0",
                 ladder="k=30,20", async_buffer=4, async_concurrency=2,
                 **BASE)
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    ctl = build_controller(cfg, sess, num_rounds=4)
    assert not ctl.policy.ADAPTS_ASYNC
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ctl.prewarm(sampler, 0.3)
    eng = AsyncFederation(cfg, sess, sampler, lambda s: 0.3, num_rounds=4)
    eng.start(0)
    for _step, _lr, m in eng.epoch_rounds(0, 0):
        assert "control/async_k" not in m
        assert "control/retunes" not in m
    eng.close()
    assert eng.stats()["retunes_applied"] == 0


def test_staleness_aware_engine_retunes_and_blob_roundtrip():
    """The closed loop end-to-end: a straggler-heavy asyncfed run under
    staleness_aware walks the ladder (>= 1 rung move), retunes the
    ENGINE's live (K, C) through the listener (cold window rebuild, the
    FedBuff trade), carries (K, C) in snapshot_extra for the vault, and
    round-trips the v3 controller blob exactly."""
    from commefficient_tpu.asyncfed import AsyncFederation

    cfg = Config(**{**_SA_KW, **BASE, "ladder": "k=30,20",
                    "async_concurrency": 3, "control_hysteresis": 1,
                    "control_staleness_hi": 0.6,
                    "control_staleness_lo": 0.2})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    ctl = build_controller(cfg, sess, num_rounds=10)
    assert ctl is not None and ctl.policy.ADAPTS_ASYNC
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    ctl.prewarm(sampler, 0.3)
    eng = AsyncFederation(cfg, sess, sampler, lambda s: 0.3, num_rounds=10)
    eng.start(0)
    ks, rungs = [], []
    for _step, _lr, m in eng.epoch_rounds(0, 0):
        assert np.isfinite(float(m["loss"]))
        ks.append(m["control/async_k"])
        rungs.append(m["control/rung"])
        assert m["control/async_k"] >= 1 and m["control/async_c"] >= 1
    eng.close()
    assert ctl.retunes > 0 and len(set(ks)) > 1, (ks, ctl.retunes)
    assert eng.stats()["retunes_applied"] >= 1
    assert len(set(rungs)) > 1, f"no ladder walk: {rungs}"
    assert sess.retrace_sentinel.retraces == 0
    # the engine's live geometry rides the vault snapshot extras
    extra = eng.snapshot_extra()
    assert extra["k"] == eng._k and extra["c"] == eng._c
    # v3 blob: (K, C, retunes) survive a fresh controller load exactly
    blob = ctl.state_blob()
    sess2 = FederatedSession(cfg, params, loss_fn)
    ctl2 = build_controller(cfg, sess2, num_rounds=10)
    ctl2.load_state_blob(blob)
    assert (ctl2.async_k, ctl2.async_c, ctl2.retunes) == (
        ctl.async_k, ctl.async_c, ctl.retunes)
