"""Pipelined round execution (pipeline/) tests.

The subsystem's one non-negotiable claim is BIT-EXACTNESS: any
``--pipeline_depth`` produces the same training as depth 0, because every
prefetched input is a pure function of the round index and dispatch/drain
order is preserved. Pinned here at three levels: the RoundWork stream vs
the synchronous realization, session-level training (plain, fedsim-masked,
and across a compression-ladder rung switch — zero retraces), and the
cv_train e2e acceptance run (bernoulli dropout + 3-rung ef_feedback
ladder + mid-run checkpoint resume). The prefetch-thread fault paths
(corrupt batch, exhausted range, fedsim realization error, dead worker)
must surface the ORIGINAL traceback at the consuming round — with a
flight dump through the runner — and shutdown must join cleanly, never
hang (the ``timeout`` marks document the bound; the tests also enforce
their own join deadlines since this container lacks pytest-timeout)."""

import json
import os
import traceback

import numpy as np
import pytest
from test_round import BASE, _setup

from commefficient_tpu.data import FedSampler
from commefficient_tpu.parallel import FederatedSession
from commefficient_tpu.pipeline import (
    PipelinedRounds,
    PrefetchWorkerDied,
    RoundPrefetcher,
)
from commefficient_tpu.utils.config import Config

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _checker():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_telemetry_schema",
        os.path.join(REPO, "scripts", "check_telemetry_schema.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _session_and_sampler(**kw):
    cfg = Config(**{**BASE, **kw})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    return cfg, sess, sampler


def _lr_fn(step):
    return 0.3 - 0.01 * step


# ---------------------------------------------------------------------------
# prefetcher: the staged stream IS the synchronous realization
# ---------------------------------------------------------------------------

def test_prefetcher_matches_synchronous_realization():
    cfg, sess, sampler = _session_and_sampler(
        mode="true_topk", error_type="virtual", k=40,
        availability="bernoulli", dropout_prob=0.3,
    )
    pf = RoundPrefetcher(session=sess, sampler=sampler, lr_fn=_lr_fn,
                         depth=2, start_step=0, stop_step=6).start()
    try:
        for step in range(6):
            work = pf.get(step)
            ids, batch = sampler.sample_round(step)
            env = sess.fedsim_env.round_env(step)
            assert work.step == step
            assert work.lr == float(_lr_fn(step))
            np.testing.assert_array_equal(work.client_ids, ids)
            for k in batch:
                # staged device arrays hold the exact host bytes
                np.testing.assert_array_equal(
                    np.asarray(work.batch[k]), batch[k]
                )
            np.testing.assert_array_equal(work.env.live, env.live)
            np.testing.assert_array_equal(work.env.corrupt, env.corrupt)
            assert work.env.stats == env.stats
            assert work.host_ms >= 0.0
    finally:
        assert pf.close()


def test_prefetcher_in_order_contract_and_exhaustion():
    cfg, sess, sampler = _session_and_sampler(mode="uncompressed")
    pf = RoundPrefetcher(session=sess, sampler=sampler, lr_fn=_lr_fn,
                         depth=2, start_step=0, stop_step=2).start()
    try:
        pf.get(0)
        with pytest.raises(RuntimeError, match="order violated"):
            pf.get(5)  # the worker staged round 1, the consumer skipped it
    finally:
        assert pf.close()
    pf = RoundPrefetcher(session=sess, sampler=sampler, lr_fn=_lr_fn,
                         depth=2, start_step=0, stop_step=1).start()
    try:
        pf.get(0)
        with pytest.raises(PrefetchWorkerDied, match="exhausted"):
            pf.get(1)  # past stop_step: a loud error, never a hang
    finally:
        assert pf.close()


# ---------------------------------------------------------------------------
# bit-exactness vs the synchronous loop (session level)
# ---------------------------------------------------------------------------

def _run_sync(cfg, sampler_seed=1, n_rounds=6):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size,
                         seed=sampler_seed)
    out = []
    for r in range(n_rounds):
        ids, batch = sampler.sample_round(r)
        env = (sess.fedsim_env.round_env(r)
               if sess.fedsim_env is not None else None)
        m = sess.train_round(ids, batch, _lr_fn(r), env=env)
        out.append(m)
    return sess, out


def _run_pipelined(cfg, sampler_seed=1, n_rounds=6):
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size,
                         seed=sampler_seed)
    engine = PipelinedRounds(cfg, sess, sampler, _lr_fn,
                             num_rounds=n_rounds,
                             steps_per_epoch=n_rounds).start(0)
    out = []
    try:
        for _s, _lr, m in engine.epoch_rounds(0, 0):
            out.append(m)
    finally:
        engine.close()
    return sess, engine, out


@pytest.mark.parametrize("kw", [
    dict(mode="sketch", error_type="virtual", virtual_momentum=0.9,
         k=40, num_rows=3, num_cols=512, pipeline_depth=2),
    dict(mode="local_topk", error_type="local", k=40, pipeline_depth=3,
         availability="bernoulli", dropout_prob=0.3),
])
def test_pipelined_training_bit_exact_vs_sync(kw):
    """Depth 2/3 training == synchronous training, bit for bit: final
    params AND every per-round device metric (fedsim-masked rounds
    included — the staged RoundEnvs are the same pure function)."""
    cfg = Config(**{**BASE, **kw})
    s_sync, m_sync = _run_sync(cfg)
    s_pipe, _, m_pipe = _run_pipelined(cfg)
    np.testing.assert_array_equal(
        np.asarray(s_sync.state.params_vec), np.asarray(s_pipe.state.params_vec)
    )
    assert len(m_sync) == len(m_pipe)
    for a, b in zip(m_sync, m_pipe):
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(np.asarray(a[k]),
                                          np.asarray(b[k]), err_msg=k)


def test_pipelined_index_path_bit_exact():
    """The device-resident index round under the pipeline: the prefetcher
    stages [W, B] sample indices + plan (stage_round_indices), dispatch
    passes the committed arrays through without a host round-trip, and
    training is bit-exact vs the synchronous index path."""
    cfg = Config(**{**BASE, "mode": "true_topk", "error_type": "virtual",
                    "k": 40, "pipeline_depth": 2})
    ds, params, loss_fn = _setup(cfg.num_clients)

    def build():
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=cfg.num_workers,
                             local_batch_size=cfg.local_batch_size, seed=1)
        assert sess.maybe_attach_data(ds, sampler), (
            "TinyMLP data must take the device-resident path"
        )
        return sess, sampler

    sess_a, sampler_a = build()
    for r in range(5):
        ids, idx, plan = sampler_a.sample_round_indices(r)
        sess_a.train_round_indices(ids, idx, plan, _lr_fn(r))
    sess_b, sampler_b = build()
    engine = PipelinedRounds(cfg, sess_b, sampler_b, _lr_fn, num_rounds=5,
                             steps_per_epoch=5).start(0)
    try:
        n = sum(1 for _ in engine.epoch_rounds(0, 0))
    finally:
        engine.close()
    assert n == 5
    np.testing.assert_array_equal(np.asarray(sess_a.state.params_vec),
                                  np.asarray(sess_b.state.params_vec))


def test_pipelined_ladder_switch_zero_retraces():
    """A mid-run rung switch under depth 2: the staged window dispatches
    through the NEW rung's prewarmed program (no restage — inputs are
    rung-invariant), the engine records the quiesce, and the sentinel
    counts zero retraces; training stays bit-exact vs the synchronous
    ladder run."""
    kw = dict(
        mode="local_topk", error_type="local", topk_method="threshold",
        telemetry_level=1, control_policy="fixed",
        control_schedule="0-2=0,3-=1", ladder="k=60,30", pipeline_depth=2,
    )
    from commefficient_tpu.control import build_controller

    def run(depth):
        cfg = Config(**{**BASE, **kw, "pipeline_depth": depth})
        ds, params, loss_fn = _setup(cfg.num_clients)
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=cfg.num_workers,
                             local_batch_size=cfg.local_batch_size, seed=1)
        ctrl = build_controller(cfg, sess, num_rounds=6)
        ctrl.prewarm(sampler, _lr_fn(0))
        if depth:
            engine = PipelinedRounds(cfg, sess, sampler, _lr_fn,
                                     num_rounds=6,
                                     steps_per_epoch=6).start(0)
            try:
                ms = [m for _s, _lr, m in engine.epoch_rounds(0, 0)]
            finally:
                engine.close()
        else:
            engine, ms = None, []
            for r in range(6):
                ids, batch = sampler.sample_round(r)
                ms.append(sess.train_round(ids, batch, _lr_fn(r)))
        return sess, ctrl, engine, ms

    s0, c0, _, m0 = run(0)
    s2, c2, eng, m2 = run(2)
    assert c0.switches == c2.switches == 1
    assert eng.quiesces == 1  # the switch listener saw the quiesce point
    assert s2.retrace_sentinel.retraces == 0
    np.testing.assert_array_equal(np.asarray(s0.state.params_vec),
                                  np.asarray(s2.state.params_vec))
    # identical rung trail; the pipelined run adds ONLY pipeline/* scalars
    for a, b in zip(m0, m2):
        assert float(np.asarray(a["control/rung"])) == \
            float(np.asarray(b["control/rung"]))
        assert set(b) - set(a) == {"pipeline/occupancy",
                                   "pipeline/host_stall_ms",
                                   "pipeline/staged_rounds"}


def test_pipeline_scalars_ride_metrics_and_validate():
    """pipeline/* scalars (level >= 1): occupancy in [0, 1],
    staged_rounds an integer <= depth — written through the real
    MetricsWriter/drain and accepted by the REAL schema checker (v5),
    which also rejects tampered values."""
    import tempfile

    from commefficient_tpu.utils.logging import MetricsWriter, \
        drain_round_metrics

    cfg = Config(**{**BASE, "mode": "uncompressed", "telemetry_level": 1,
                    "pipeline_depth": 2})
    _, _, out = _run_pipelined(cfg, n_rounds=4)
    for m in out:
        occ = float(np.asarray(m["pipeline/occupancy"]))
        staged = float(np.asarray(m["pipeline/staged_rounds"]))
        assert 0.0 <= occ <= 1.0
        assert staged == int(staged) and 0 <= staged <= 2
        assert occ == staged / 2
        assert float(np.asarray(m["pipeline/host_stall_ms"])) >= 0.0
    with tempfile.TemporaryDirectory() as td:
        writer = MetricsWriter(td, cfg=cfg)
        pending = [(i, 0.1, m) for i, m in enumerate(out)]
        drain_round_metrics(pending, writer, lambda *a: None)
        writer.close()
        mod = _checker()
        assert mod.validate_metrics_jsonl(os.path.join(td, "metrics.jsonl"))
        # rejection self-tests: the checker enforces the v5 invariants
        path = os.path.join(td, "bad.jsonl")
        header = open(os.path.join(td, "metrics.jsonl")).readline()
        for bad, msg in [
            ({"name": "pipeline/occupancy", "value": 1.5, "step": 0,
              "t": 0.0}, "outside"),
            ({"name": "pipeline/staged_rounds", "value": 1.5, "step": 0,
              "t": 0.0}, "integer"),
            ({"name": "pipeline/occupancy", "value": "nan", "step": 0,
              "t": 0.0}, "finite"),
        ]:
            with open(path, "w") as f:
                f.write(header)
                f.write(json.dumps(bad) + "\n")
            with pytest.raises(mod.SchemaError, match=msg):
                mod.validate_metrics_jsonl(path)


def test_spans_thread_aware_prefetch_lane(tmp_path):
    """Schema v5 thread-aware spans: the prefetch worker's spans land on
    their OWN lane (tid != 0) with a thread_name metadata event and the
    step they realize; dispatch spans stay on lane 0. The dump passes the
    real checker."""
    from commefficient_tpu.telemetry.spans import PhaseSpans

    cfg, sess, sampler = _session_and_sampler(mode="uncompressed",
                                              telemetry_level=1)
    spans = PhaseSpans(str(tmp_path))
    sess.spans = spans
    engine = PipelinedRounds(cfg.replace(pipeline_depth=2), sess, sampler,
                             _lr_fn, num_rounds=4, steps_per_epoch=4,
                             spans=spans).start(0)
    try:
        for _ in engine.epoch_rounds(0, 0):
            pass
    finally:
        engine.close()
    sess.spans = None
    path = spans.close()
    rec = _checker().validate_spans(path)
    evs = rec["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    assert any(e["args"]["name"] == "round-prefetch" for e in meta)
    lane = next(e["tid"] for e in meta
                if e["args"]["name"] == "round-prefetch")
    assert lane != 0
    pre = [e for e in evs if e["ph"] == "X"
           and e["name"].startswith("prefetch_")]
    assert pre and all(e["tid"] == lane for e in pre)
    # the prefetch lane stamps the round it REALIZES, not the consumer's
    # current round clock
    assert sorted({e["args"]["step"] for e in pre
                   if e["name"] == "prefetch_realize"}) == [0, 1, 2, 3]
    disp = [e for e in evs if e["ph"] == "X"
            and e["name"] == "round_dispatch"]
    assert disp and all(e["tid"] == 0 for e in disp)


# ---------------------------------------------------------------------------
# fault paths: original traceback at the consuming round, never a hang
# ---------------------------------------------------------------------------

class _PoisonedSampler:
    """Delegates to a real FedSampler but corrupts round ``bad_round``."""

    def __init__(self, real, bad_round, exc):
        self._real = real
        self._bad = bad_round
        self._exc = exc

    def steps_per_epoch(self):
        return self._real.steps_per_epoch()

    def sample_round(self, r):
        if r == self._bad:
            raise self._exc
        return self._real.sample_round(r)


@pytest.mark.timeout(120)
def test_worker_fault_surfaces_original_traceback():
    """A corrupt batch at round 3 raises AT the consuming round 3 —
    original exception object, worker-side frames intact — after rounds
    0..2 trained normally; close() still joins."""
    cfg, sess, sampler = _session_and_sampler(mode="uncompressed",
                                              pipeline_depth=2)
    poisoned = _PoisonedSampler(sampler, 3,
                                ValueError("corrupt batch payload"))
    engine = PipelinedRounds(cfg, sess, poisoned, _lr_fn, num_rounds=6,
                             steps_per_epoch=6).start(0)
    try:
        seen = []
        with pytest.raises(ValueError, match="corrupt batch payload") as ei:
            for s, _lr, _m in engine.epoch_rounds(0, 0):
                seen.append(s)
        assert seen == [0, 1, 2]
        frames = "".join(traceback.format_tb(ei.value.__traceback__))
        assert "_realize" in frames, (
            "the worker-side traceback must survive the thread hop"
        )
    finally:
        engine.close()
    # the prefetcher must be joinable after the fault (bounded deadline)
    assert engine._prefetcher.close(timeout=10.0)


@pytest.mark.timeout(120)
def test_fedsim_realization_fault_surfaces():
    """A fedsim env realization error in the worker surfaces at the
    consuming round with the original frames (the 'fedsim validation
    error' fault class)."""
    cfg, sess, sampler = _session_and_sampler(
        mode="uncompressed", availability="bernoulli", dropout_prob=0.2,
        pipeline_depth=2,
    )

    def boom(round_idx, replay=False):
        raise RuntimeError(f"fedsim validation failed at {round_idx}")

    sess.fedsim_env.round_env = boom
    engine = PipelinedRounds(cfg, sess, sampler, _lr_fn, num_rounds=4,
                             steps_per_epoch=4).start(0)
    try:
        with pytest.raises(RuntimeError, match="fedsim validation failed"):
            for _ in engine.epoch_rounds(0, 0):
                pass
    finally:
        engine.close()
    assert engine._prefetcher.close(timeout=10.0)


@pytest.mark.timeout(120)
def test_worker_exit_does_not_mask_staged_items_or_faults(monkeypatch):
    """A finished/dead worker must never shadow what it already staged:
    items (and the exhaustion sentinel) enqueued before the thread exited
    are still consumed in order; only a worker that died WITHOUT leaving
    an item or exception raises the generic PrefetchWorkerDied."""
    cfg, sess, sampler = _session_and_sampler(mode="uncompressed")
    pf = RoundPrefetcher(session=sess, sampler=sampler, lr_fn=_lr_fn,
                         depth=3, start_step=0, stop_step=2).start()
    pf._thread.join(timeout=30)  # 2 rounds + _END fit the depth-3 queue
    assert not pf._thread.is_alive()
    # the gauge counts only real WORK: the queue holds 3 items here but
    # the _END sentinel must not inflate staged_rounds/occupancy
    assert pf.staged_rounds == 2
    assert pf.get(0).step == 0
    assert pf.staged_rounds == 1
    assert pf.get(1).step == 1
    with pytest.raises(PrefetchWorkerDied, match="exhausted"):
        pf.get(2)
    assert pf.close()
    # the genuinely-dead case: the worker exits without staging anything
    # (simulated hard death) — a loud, honest error, not a hang
    monkeypatch.setattr(RoundPrefetcher, "_run", lambda self: None)
    dead = RoundPrefetcher(session=sess, sampler=sampler, lr_fn=_lr_fn,
                           depth=2, start_step=0, stop_step=4).start()
    dead._thread.join(timeout=30)
    with pytest.raises(PrefetchWorkerDied, match="died before staging"):
        dead.get(0)
    assert dead.close()


@pytest.mark.timeout(120)
def test_shutdown_joins_cleanly_with_staged_window():
    """Abandoning a full in-flight window (consumer stops early) must
    join the worker within the deadline — the bounded-queue put polls the
    stop flag, so a full queue cannot deadlock shutdown."""
    cfg, sess, sampler = _session_and_sampler(mode="uncompressed")
    pf = RoundPrefetcher(session=sess, sampler=sampler, lr_fn=_lr_fn,
                         depth=3, start_step=0, stop_step=100).start()
    pf.get(0)  # worker is live and the window refills behind this
    assert pf.close(timeout=10.0), "prefetch worker failed to join"
    assert not pf._thread.is_alive()


@pytest.mark.timeout(120)
def test_runner_flight_dump_on_worker_fault(tmp_path):
    """The full-loop contract: a prefetch-worker fault crashes the shared
    runner, which drains the dispatched in-flight rounds (true round
    indices in the ledger/flight) and dumps a flight record for the
    post-mortem — same forensics as a synchronous crash."""
    from commefficient_tpu.train.cv_train import train_loop
    from commefficient_tpu.utils.logging import MetricsWriter

    cfg = Config(**{**BASE, "mode": "uncompressed", "telemetry_level": 1,
                    "pipeline_depth": 2, "num_epochs": 1,
                    "perf_audit": False, "local_batch_size": 4})
    ds, params, loss_fn = _setup(cfg.num_clients)
    sess = FederatedSession(cfg, params, loss_fn)
    sampler = FedSampler(ds, num_workers=cfg.num_workers,
                         local_batch_size=cfg.local_batch_size, seed=1)
    poisoned = _PoisonedSampler(sampler, 4, ValueError("bad round 4"))
    test_ds = ds  # never reached: the crash fires before epoch-end eval
    writer = MetricsWriter(str(tmp_path / "run"), cfg=cfg)
    with pytest.raises(ValueError, match="bad round 4"):
        train_loop(cfg, sess, poisoned, test_ds, writer)
    writer.close()
    run_dir = tmp_path / "run"
    flights = list(run_dir.glob("flight_*.json"))
    assert flights, "worker fault must dump a flight record"
    rec = _checker().validate_flight(flights[0])
    assert "bad round 4" in rec["reason"]
    # the dispatched rounds 0..3 were drained with their true indices
    assert [r["step"] for r in rec["records"]] == [0, 1, 2, 3]
    ledger = run_dir / "comm_ledger.json"
    assert _checker().validate_comm_ledger(ledger)["rounds"] == 4


# ---------------------------------------------------------------------------
# the full runner path at TinyMLP scale (default-tier twin of the e2e)
# ---------------------------------------------------------------------------

def test_runner_pipelined_resume_bit_exact_tinymlp(tmp_path):
    """The cv_train e2e's default-tier twin on the TinyMLP task: the REAL
    shared runner (train_loop) at depth 2 vs depth 0 under bernoulli
    dropout + a 3-rung ef_feedback ladder — bit-identical final params
    and metrics.jsonl scalar sequence, >= 1 rung switch, zero retraces —
    and a resume from a mid-run checkpoint reproduces the tail."""
    from commefficient_tpu.data import FedDataset
    from commefficient_tpu.train.cv_train import train_loop
    from commefficient_tpu.utils.checkpoint import FedCheckpointer
    from commefficient_tpu.utils.logging import MetricsWriter

    ds, params, loss_fn = _setup(12)
    test_ds = FedDataset({"x": ds.data["x"][:40], "y": ds.data["y"][:40]},
                         1, seed=0)

    def run(depth, tag, resume=False):
        cfg = Config(**{**BASE, **dict(
            mode="true_topk", error_type="virtual", virtual_momentum=0.9,
            topk_method="threshold", telemetry_level=1, perf_audit=False,
            availability="bernoulli", dropout_prob=0.25,
            control_policy="ef_feedback", ladder="k=60,30,15",
            control_ef_up=1e-9, control_ef_down=-1.0, control_hysteresis=1,
            num_epochs=1, pivot_epoch=1, lr_scale=0.1,
            checkpoint_dir=str(tmp_path / f"ckpt{tag}"), checkpoint_every=5,
            pipeline_depth=depth, resume=resume,
        )})
        sess = FederatedSession(cfg, params, loss_fn)
        sampler = FedSampler(ds, num_workers=cfg.num_workers,
                             local_batch_size=cfg.local_batch_size, seed=1)
        run_dir = str(tmp_path / f"run{tag}" / ("resume" if resume else "full"))
        writer = MetricsWriter(run_dir, cfg=cfg)
        ck = FedCheckpointer(cfg)
        try:
            train_loop(cfg, sess, sampler, test_ds, writer,
                       eval_batch_size=32, checkpointer=ck)
        finally:
            ck.close()
            writer.close()
        return sess, run_dir

    s0, dir0 = run(0, "_d0")
    s2, dir2 = run(2, "_d2")
    np.testing.assert_array_equal(np.asarray(s0.state.params_vec),
                                  np.asarray(s2.state.params_vec))
    seq0, seq2 = _scalar_sequence(dir0), _scalar_sequence(dir2)
    assert seq0 and seq0 == seq2
    rungs = [v for n, v, _s in seq2 if n == "control/rung"]
    assert rungs[0] == 2.0 and len(set(rungs)) >= 2, rungs
    assert {v for n, v, _s in seq2 if n == "xla/retraces"} == {0.0}
    assert s2.retrace_sentinel.retraces == 0
    # resume: drop all but the FIRST surviving checkpoint and replay
    import shutil

    kept = sorted(int(p.name) for p in (tmp_path / "ckpt_d2").iterdir()
                  if p.name.isdigit())
    resume_step = kept[0]
    steps_total = max(s for _n, _v, s in seq0)
    assert resume_step < steps_total, kept
    for s in kept[1:]:
        shutil.rmtree(tmp_path / "ckpt_d2" / str(s))
    s2r, dir2r = run(2, "_d2", resume=True)
    np.testing.assert_array_equal(np.asarray(s0.state.params_vec),
                                  np.asarray(s2r.state.params_vec))
    drop = ("comm/",)  # process-local cumulative ledger, by design
    tail = [r for r in _scalar_sequence(dir2r)
            if r[2] >= resume_step and not r[0].startswith(drop)]
    want = [r for r in seq0 if r[2] >= resume_step
            and not r[0].startswith(drop)]
    assert tail == want, "resume diverged from the uninterrupted run"


# ---------------------------------------------------------------------------
# cv_train e2e (the PR acceptance pin)
# ---------------------------------------------------------------------------

def _scalar_sequence(logdir, *, exclude_prefix="pipeline/"):
    """Every scalar record under ``logdir`` as (name, value, step) tuples
    in file order — the bit-exactness comparison unit (wall-time ``t`` is
    the only field that may differ between twin runs). ``pipeline/*`` is
    excluded: those gauges exist only at depth > 0 by design, and
    ``xla/exposed_collective_ms`` (v9) plus ``trace/*`` (v11) because
    they are host-measured wall-clock attribution."""
    out = []
    for root, _, files in os.walk(logdir):
        for f in sorted(files):
            if f != "metrics.jsonl":
                continue
            with open(os.path.join(root, f)) as fh:
                for line in fh:
                    rec = json.loads(line)
                    if "name" not in rec:
                        continue  # run header
                    if rec["name"].startswith(
                        (exclude_prefix, "trace/", "xla/exposed_collective_ms")
                    ):
                        continue
                    out.append((rec["name"], rec["value"], rec["step"]))
    return out


def _final_params(ckpt_dir):
    """The final checkpoint's saved fed_state leaves (raw numpy)."""
    import orbax.checkpoint as ocp

    mngr = ocp.CheckpointManager(os.path.abspath(ckpt_dir))
    restored = mngr.restore(mngr.latest_step(),
                            args=ocp.args.StandardRestore())
    mngr.close()
    return restored["fed_state"]


@pytest.mark.slow  # ~4-5 min of femnist/resnet9 compiles (3 cv_main runs)
# on the 1-core CPU budget; every claim it pins holds DEFAULT-tier
# coverage at TinyMLP scale through the same shared runner
# (test_runner_pipelined_resume_bit_exact_tinymlp + the session-level
# bit-exactness tests above) — this is the full-entry artifact check,
# same discipline as test_cv_train_budget_hard_stop_e2e
def test_cv_train_pipeline_depth2_bit_exact_e2e(tmp_path):
    """Acceptance: cv_train at --pipeline_depth 2 produces bit-identical
    final params and metrics.jsonl scalar sequence vs --pipeline_depth 0,
    under a bernoulli-dropout fedsim env AND a 3-rung ef_feedback ladder
    (identical rung sequence, xla/retraces == 0 throughout), and a resume
    from a mid-run checkpoint reproduces it. Checkpoint boundaries force
    mid-epoch drains, so the policy decides on mid-epoch telemetry — the
    hardest case for the depth-parity claim."""
    from commefficient_tpu.train.cv_train import main as cv_main

    def kw(depth, tag):
        return dict(
            dataset_name="femnist",
            model="resnet9",
            mode="true_topk",
            error_type="virtual",
            virtual_momentum=0.9,
            topk_method="threshold",
            num_clients=6,
            num_workers=4,
            num_devices=4,
            local_batch_size=32,  # 5 rounds/epoch on the femnist stand-in
            num_epochs=2,
            pivot_epoch=1,
            lr_scale=0.1,
            dataset_dir=str(tmp_path),
            seed=0,
            telemetry_level=1,
            perf_audit=False,  # the AOT audit is test_xla_audit territory
            availability="bernoulli",
            dropout_prob=0.25,
            control_policy="ef_feedback",
            ladder="k=4000,2000,1000",
            # force deterministic climbing: any EF growth climbs, and the
            # bank grows from zero in the first rounds by construction
            control_ef_up=1e-9,
            control_ef_down=-1.0,
            control_hysteresis=1,
            # checkpoints every 3 rounds: mid-epoch drains (policy decides
            # mid-epoch) AND the resume leg's restore point. The schedule
            # is config, hence identical across depths — drain points are
            # part of the determinism contract.
            checkpoint_dir=str(tmp_path / f"ckpt{tag}"),
            checkpoint_every=3,
            pipeline_depth=depth,
            logdir=str(tmp_path / f"runs{tag}"),
        )

    cv_main([], **kw(0, "_d0"))
    cv_main([], **kw(2, "_d2"))
    seq0 = _scalar_sequence(tmp_path / "runs_d0")
    seq2 = _scalar_sequence(tmp_path / "runs_d2")
    assert seq0, "depth-0 run wrote no scalars"
    assert seq0 == seq2, "depth 2 diverged from depth 0 bitwise"
    rungs = [v for n, v, _s in seq2 if n == "control/rung"]
    assert rungs[0] == 2.0, "ef_feedback starts at the cheapest rung"
    assert len(set(rungs)) >= 2, f"no rung switch happened: {rungs}"
    assert {v for n, v, _s in seq2 if n == "xla/retraces"} == {0.0}, (
        "the pipelined ladder run must stay retrace-free"
    )
    # depth-2's pipeline gauges exist and respect the schema invariants
    occ = [v for n, v, _s in _scalar_sequence(
        tmp_path / "runs_d2", exclude_prefix="\0"
    ) if n == "pipeline/occupancy"]
    assert occ and all(0.0 <= v <= 1.0 for v in occ)
    # final params: bit-identical across depths (the forced final save)
    fs0 = _final_params(tmp_path / "ckpt_d0")
    fs2 = _final_params(tmp_path / "ckpt_d2")
    for leaf in ("params_vec", "momentum", "error", "step"):
        np.testing.assert_array_equal(
            np.asarray(fs0[leaf]), np.asarray(fs2[leaf]), err_msg=leaf
        )
    # resume leg: drop all but the FIRST mid-run checkpoint (a kill at
    # that round) and replay at depth 2 — the resumed run reproduces the
    # uninterrupted scalar/rung sequence from the restore point on
    kept = sorted(int(p.name) for p in (tmp_path / "ckpt_d2").iterdir()
                  if p.name.isdigit())
    resume_step = kept[0]
    assert resume_step < 10, f"no mid-run checkpoint survived: {kept}"
    import shutil

    for s in kept[1:]:
        shutil.rmtree(tmp_path / "ckpt_d2" / str(s))
    cv_main([], resume=True,
            **{**kw(2, "_d2"), "logdir": str(tmp_path / "runs_resume")})

    def _no_comm(rows):
        # comm/* cumulative bytes are PROCESS-local by design (each
        # process's own ledger, exact over the rounds it drained — the
        # checker validates that law per run dir), so the resumed
        # process's comm scalars legitimately differ from the
        # uninterrupted run's; everything else must match bitwise.
        return [r for r in rows if not r[0].startswith("comm/")]

    tail = _no_comm([r for r in _scalar_sequence(tmp_path / "runs_resume")
                     if r[2] >= resume_step])
    want = _no_comm([r for r in seq0 if r[2] >= resume_step])
    assert tail == want, "resume diverged from the uninterrupted run"
